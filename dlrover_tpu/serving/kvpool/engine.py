"""Paged serving engine: block-table KV over a shared block pool.

The flat engine (serving/engine.py) reserves one ``[max_len]`` KV row
per slot — a 20-token request pins as much HBM as a 1024-token one, and
a shared system prompt re-prefills from scratch in every slot. Here the
cache is ``[layers, num_blocks, block_size, kv_heads, head_dim]`` and a
slot's logical cache is the pool rows its BLOCK TABLE names:

- **Block tables as traced args.** The ``[slots, max_blocks]`` int32
  tables ride into the compiled steps exactly like the fill vector:
  every admission/allocation/COW changes table VALUES, never shapes, so
  the no-retrace-across-admissions property survives paging. Inside
  the decode step each layer gathers its per-slot logical view through
  the table and runs the SAME append-free ragged attention as the flat
  engine (``models/generate._layer_decode_read_only``) — token-exact by
  construction. The append is a per-slot scatter at ``(table[cursor //
  bs], cursor % bs)``; non-active slots are redirected to the reserved
  SENTINEL block 0 so their masked-garbage writes can never land in a
  block another slot shares (the flat engine's own-row trick does not
  survive sharing). Note on the hot path: the XLA gather reads the
  same ``[slots, max_len]`` logical view per layer the FLAT engine's
  append-free step already reads — paging's win here is CAPACITY
  (blocks per admitted token), not per-step bandwidth. The
  length-clamped Pallas variant (``ops.decode_attention.
  paged_decode_attention``, parity-tested) is the TPU-targeted
  alternative, deliberately not the default for the same measured
  reason as the flat engine's (§21): the per-(batch, kv-head) grid
  serializes on TPU and loses to the XLA step at serving shapes.
- **Visibility invariant, unchanged.** A logical row is read iff
  ``row < fill``; stale or foreign content beyond a slot's fill —
  including the longer tail of a SHARED prefix block — is masked out
  per slot, per row (docs/DESIGN.md §31).
- **Cross-request prefix cache.** Admission hashes the prompt's full
  blocks against the :class:`PrefixCache`; a hit slots the warm chain
  straight into the block table and prefill SKIPS the covered chunks
  (TTFT drops by the skipped chunk iterations). Shared blocks are
  refcounted and immutable: the one legal rewrite (a chunk-aligned
  re-prefill over a shared block, identical values) privatizes first
  via copy-on-write — a small compiled block-copy program, counted in
  ``trace_counts`` like its siblings.
- **Oversubscription + relief.** ``num_blocks`` may be far below
  ``slots * max_blocks`` (short requests hold few blocks — that is the
  capacity win). When the pool runs dry the engine first evicts
  prefix-cache LRU chains, then PREEMPTS the youngest active request
  (front-requeued with progress reset, no requeue-budget charge); an
  engine is constructed with room for at least one full-length slot,
  so relief always terminates.
"""

import functools
import time
from typing import Dict, List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import logger
from dlrover_tpu.models import generate as gen_lib
from dlrover_tpu.models import llama
from dlrover_tpu.serving.engine import ServingEngine
from dlrover_tpu.serving.kvpool.allocator import (
    BlockAllocator,
    BlockPoolExhausted,
)
from dlrover_tpu.serving.kvpool.prefix_cache import PrefixCache
from dlrover_tpu.serving import spec_decode as spec_lib
from dlrover_tpu.serving.scheduler import DECODE, PREFILL, Request

# Pool row 0 absorbs the masked-garbage appends of non-active slots;
# never allocated, never read.
SENTINEL_BLOCK = 0


class _PagedSteps(NamedTuple):
    prefill: object
    decode: object
    cow: object
    imp: object          # migration import: host block rows -> pool[dst]
    exp: object          # migration export: pool[src] -> one block's rows
    trace_counts: Dict[str, int]


class _PagedSpecSteps(NamedTuple):
    """Speculative verify/draft programs over the block pool —
    compiled separately from _PagedSteps for the same reason as the
    flat engine's _SpecSteps: spec on/off engines share the base
    programs."""

    verify: object
    draft: object        # None for the host-side n-gram drafter
    trace_counts: Dict[str, int]


def _build_paged_decode(config, slots: int, max_blocks: int,
                        block_size: int, counts,
                        quantized: bool = False):
    """[slots] tokens -> one decoded token per slot, ragged lengths,
    cache gathered per layer through the block tables. ``quantized``:
    int8 pools + per-(row, head) scale pools — the gather streams half
    the KV bytes and the append quantizes each new row (ops/kv_quant);
    dequantization folds into the attention math."""
    max_len = max_blocks * block_size
    kh, hd = config.n_kv_heads, config.head_dim

    def _append_coords(tables, lengths, active):
        # Per-slot append through the table. Non-active slots are
        # redirected to the sentinel block: their garbage must never
        # land in a block another slot may SHARE (the flat engine's
        # own-row invisibility does not survive sharing). Active slots
        # write their privately-owned cursor block (host COW-ensured).
        write = jnp.minimum(lengths, max_len - 1)
        blk = jnp.take_along_axis(
            tables, (write // block_size)[:, None], axis=1
        )[:, 0]
        blk = jnp.where(active, blk, SENTINEL_BLOCK)
        off = jnp.where(active, write % block_size, 0)
        return blk, off

    def _finish(x, params, rng, step_idx, temps, active, tokens):
        logits = llama.unembed(config, params, x)[:, 0]   # [slots, V]
        sub = jax.random.fold_in(rng, step_idx * 2)
        nxt = gen_lib.sample_token(logits, sub, temps)
        return jnp.where(active, nxt, tokens)

    def step(k, v, params, tables, lengths, tokens, active, temps,
             rng, step_idx):
        counts["decode"] += 1  # traces only
        positions = lengths[:, None]                     # [slots, 1]
        x = llama.embed_tokens(config, params, tokens[:, None])

        def body(carry, layer_in):
            pl, k_c, v_c = layer_in                      # [nb, bs, kh, hd]
            k_view = k_c[tables].reshape(slots, max_len, kh, hd)
            v_view = v_c[tables].reshape(slots, max_len, kh, hd)
            y, k_new, v_new = gen_lib._layer_decode_read_only(
                config, pl, carry, positions, k_view, v_view, lengths
            )
            return y, (k_new, v_new)

        x, (k_news, v_news) = jax.lax.scan(
            body, x, (params["layers"], k, v)
        )
        blk, off = _append_coords(tables, lengths, active)
        k = k.at[:, blk, off].set(k_news[:, :, 0].astype(k.dtype))
        v = v.at[:, blk, off].set(v_news[:, :, 0].astype(v.dtype))
        nxt = _finish(x, params, rng, step_idx, temps, active, tokens)
        return k, v, nxt

    def step_q8(k, v, ks, vs, params, tables, lengths, tokens, active,
                temps, rng, step_idx):
        from dlrover_tpu.ops.kv_quant import quantize_kv

        counts["decode"] += 1  # traces only
        positions = lengths[:, None]
        x = llama.embed_tokens(config, params, tokens[:, None])

        def body(carry, layer_in):
            pl, k_c, v_c, ks_c, vs_c = layer_in
            k_view = k_c[tables].reshape(slots, max_len, kh, hd)
            v_view = v_c[tables].reshape(slots, max_len, kh, hd)
            ks_view = ks_c[tables].reshape(slots, max_len, kh)
            vs_view = vs_c[tables].reshape(slots, max_len, kh)
            y, k_new, v_new = gen_lib._layer_decode_read_only(
                config, pl, carry, positions, k_view, v_view, lengths,
                k_scale=ks_view, v_scale=vs_view,
            )
            return y, (k_new, v_new)

        x, (k_news, v_news) = jax.lax.scan(
            body, x, (params["layers"], k, v, ks, vs)
        )
        blk, off = _append_coords(tables, lengths, active)
        kq, ks_rows = quantize_kv(k_news[:, :, 0])   # [L, slots, kh, hd]
        vq, vs_rows = quantize_kv(v_news[:, :, 0])
        k = k.at[:, blk, off].set(kq)
        v = v.at[:, blk, off].set(vq)
        ks = ks.at[:, blk, off].set(ks_rows)
        vs = vs.at[:, blk, off].set(vs_rows)
        nxt = _finish(x, params, rng, step_idx, temps, active, tokens)
        return k, v, ks, vs, nxt

    return step_q8 if quantized else step


def _build_paged_prefill(config, max_blocks: int, block_size: int,
                         chunk: int, counts, quantized: bool = False):
    """One prompt chunk into ONE slot's blocks: gather the slot's
    logical cache through its table row, run the flat prefill body,
    scatter back only the touched blocks (shared untouched blocks are
    never rewritten — the COW invariant). ``quantized``: the slot view
    is dequantized for the (compute-bound) chunk forward and the
    touched span re-quantized on the way out — per-(row, head)
    round-to-nearest is IDEMPOTENT (the amax element always maps to
    ±127), so rows below the chunk inside a touched block keep their
    exact stored values."""
    L = config.n_layers
    kh, hd = config.n_kv_heads, config.head_dim
    max_len = max_blocks * block_size
    # Blocks a chunk can touch: chunk//bs full blocks when chunks are
    # block-multiples, else the single block containing the chunk
    # (init enforces one of chunk % bs == 0 / bs % chunk == 0).
    n_touch = max(chunk // block_size, 1)

    def _run_chunk(k_slot, v_slot, params, tokens, start):
        positions = (
            start + jnp.arange(chunk, dtype=jnp.int32)
        )[None, :]
        x = llama.embed_tokens(config, params, tokens)

        def body(carry, layer_in):
            pl, k_c, v_c = layer_in
            y, k_c, v_c = gen_lib._layer_decode(
                config, pl, carry, positions, k_c, v_c, start,
                attn_impl="xla",
            )
            return y, (k_c, v_c)

        return jax.lax.scan(
            body, x, (params["layers"], k_slot, v_slot)
        )

    def _touched(arr, start, head_shape):
        # Slice the touched span [touched0*bs, +n_touch*bs) — it
        # covers [start, start+chunk) exactly (chunk-aligned starts;
        # see the divisibility contract), so shared UNtouched blocks
        # are never rewritten.
        touched0 = start // block_size
        seg = jax.lax.dynamic_slice(
            arr, (0, 0, touched0 * block_size) + (0,) * len(head_shape),
            (L, 1, n_touch * block_size) + head_shape,
        ).reshape((L, n_touch, block_size) + head_shape)
        return seg, touched0

    def _first_token(x, params, n_valid, temp, rng, step_idx):
        h = jax.lax.dynamic_slice_in_dim(x, n_valid - 1, 1, axis=1)
        logits = llama.unembed(config, params, h)[0, 0]    # [V]
        sub = jax.random.fold_in(rng, step_idx * 2 + 1)
        return gen_lib.sample_token(logits, sub, temp)

    def prefill(k, v, params, tokens, table_row, start, n_valid, temp,
                rng, step_idx):
        counts["prefill"] += 1  # traces only
        k_slot = k[:, table_row].reshape(L, 1, max_len, kh, hd)
        v_slot = v[:, table_row].reshape(L, 1, max_len, kh, hd)
        x, (k_slot, v_slot) = _run_chunk(
            k_slot, v_slot, params, tokens, start
        )
        seg_k, touched0 = _touched(k_slot, start, (kh, hd))
        seg_v, _ = _touched(v_slot, start, (kh, hd))
        ids = jax.lax.dynamic_slice(table_row, (touched0,), (n_touch,))
        k = k.at[:, ids].set(seg_k.astype(k.dtype))
        v = v.at[:, ids].set(seg_v.astype(v.dtype))
        first = _first_token(x, params, n_valid, temp, rng, step_idx)
        return k, v, first

    def prefill_q8(k, v, ks, vs, params, tokens, table_row, start,
                   n_valid, temp, rng, step_idx):
        from dlrover_tpu.ops.kv_quant import dequantize_kv, quantize_kv

        counts["prefill"] += 1  # traces only
        k_q = k[:, table_row].reshape(L, 1, max_len, kh, hd)
        v_q = v[:, table_row].reshape(L, 1, max_len, kh, hd)
        ks_slot = ks[:, table_row].reshape(L, 1, max_len, kh)
        vs_slot = vs[:, table_row].reshape(L, 1, max_len, kh)
        # f32 view, not compute_dtype: q*scale is exact in f32, so the
        # round trip is idempotent and untouched rows inside touched
        # blocks re-quantize to their exact stored (values, scale).
        k_slot = dequantize_kv(k_q, ks_slot, jnp.float32)
        v_slot = dequantize_kv(v_q, vs_slot, jnp.float32)
        x, (k_slot, v_slot) = _run_chunk(
            k_slot, v_slot, params, tokens, start
        )
        kq_new, ks_new = quantize_kv(k_slot)
        vq_new, vs_new = quantize_kv(v_slot)
        seg_k, touched0 = _touched(kq_new, start, (kh, hd))
        seg_v, _ = _touched(vq_new, start, (kh, hd))
        seg_ks, _ = _touched(ks_new, start, (kh,))
        seg_vs, _ = _touched(vs_new, start, (kh,))
        ids = jax.lax.dynamic_slice(table_row, (touched0,), (n_touch,))
        k = k.at[:, ids].set(seg_k)
        v = v.at[:, ids].set(seg_v)
        ks = ks.at[:, ids].set(seg_ks)
        vs = vs.at[:, ids].set(seg_vs)
        first = _first_token(x, params, n_valid, temp, rng, step_idx)
        return k, v, ks, vs, first

    return prefill_q8 if quantized else prefill


def _build_cow_copy(counts, quantized: bool = False):
    """Device block copy src -> dst (both K and V, all layers, plus
    the scale pools for int8): the copy-on-write primitive. src/dst
    are traced scalars — privatizing any block never retraces."""

    def cow(k, v, src, dst):
        counts["cow"] += 1  # traces only
        k = k.at[:, dst].set(k[:, src])
        v = v.at[:, dst].set(v[:, src])
        return k, v

    def cow_q8(k, v, ks, vs, src, dst):
        counts["cow"] += 1  # traces only
        k = k.at[:, dst].set(k[:, src])
        v = v.at[:, dst].set(v[:, src])
        ks = ks.at[:, dst].set(ks[:, src])
        vs = vs.at[:, dst].set(vs[:, src])
        return k, v, ks, vs

    return cow_q8 if quantized else cow


def _build_import_scatter(counts, quantized: bool = False):
    """Migration import (kvpool/migrate, §36): land one migrated
    block's rows — host data, shape [L, block_size, kh, hd] (+ scale
    rows for int8) — at pool row ``dst``. ``dst`` is a traced scalar
    like the COW src/dst, so importing any number of requests into any
    blocks never retraces."""

    def imp(k, v, dk, dv, dst):
        counts["imp"] += 1  # traces only
        k = k.at[:, dst].set(dk.astype(k.dtype))
        v = v.at[:, dst].set(dv.astype(v.dtype))
        return k, v

    def imp_q8(k, v, ks, vs, dk, dv, dks, dvs, dst):
        counts["imp"] += 1  # traces only
        k = k.at[:, dst].set(dk)
        v = v.at[:, dst].set(dv)
        ks = ks.at[:, dst].set(dks)
        vs = vs.at[:, dst].set(dvs)
        return k, v, ks, vs

    return imp_q8 if quantized else imp


def _build_export_gather(counts, quantized: bool = False):
    """Migration export (kvpool/migrate, §36): read one block's rows
    out of the pool at row ``src`` — the gather mirror of the import
    scatter. ``src`` is a traced scalar, so exporting a request of ANY
    block count is n calls of one compiled program; the jnp
    fancy-index alternative (``k[:, ids]``) recompiles per block-count
    and stalled the serve loop ~400ms per new shape on CPU. No pool
    donation: the request stays live on the source until released."""

    def exp(k, v, src):
        counts["exp"] += 1  # traces only
        return k[:, src], v[:, src]

    def exp_q8(k, v, ks, vs, src):
        counts["exp"] += 1  # traces only
        return k[:, src], v[:, src], ks[:, src], vs[:, src]

    return exp_q8 if quantized else exp


def _build_paged_verify(config, slots: int, max_blocks: int,
                        block_size: int, K: int, counts,
                        quantized: bool = False):
    """Paged sibling of serving.engine._build_verify_step: the T = K+1
    verification queries gather each slot's logical cache through its
    block table and all T new rows land via one advanced-index scatter
    at block coordinates. Invalid writes (inactive slot, or a row at or
    past max_len) are redirected to the sentinel block — the paged
    engine's version of ``mode="drop"``; the host guarantees the rows
    that CAN become visible (fill..fill+accept) sit in allocated,
    privately-owned blocks (_spec_prepare_rows). ``quantized``: the
    layer quantizes its new rows IN-LAYER (per-row round-to-nearest, so
    intra-draft reads see exactly the values a sequential step would
    read back from the int8 cache — the bit-stability rule, §35) and
    the scatter appends the quantized rows + scales directly."""
    max_len = max_blocks * block_size
    kh, hd = config.n_kv_heads, config.head_dim
    T = K + 1

    def _verify_coords(tables, lengths, active):
        writes = (
            lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        )                                                # [slots, T]
        valid = active[:, None] & (writes < max_len)
        w = jnp.minimum(writes, max_len - 1)
        blk = jnp.take_along_axis(tables, w // block_size, axis=1)
        blk = jnp.where(valid, blk, SENTINEL_BLOCK)
        off = jnp.where(valid, w % block_size, 0)
        # Several invalid columns may collapse onto sentinel (0, 0);
        # duplicate scatter targets are fine — it is garbage writing
        # over garbage in a block that is never read.
        return blk, off

    def verify(k, v, params, tables, lengths, tokens, drafts,
               draft_len, active, temps, rng, step_idx):
        counts["verify"] += 1  # traces only
        toks = jnp.concatenate([tokens[:, None], drafts], axis=1)
        positions = (
            lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        )
        x = llama.embed_tokens(config, params, toks)

        def body(carry, layer_in):
            pl, k_c, v_c = layer_in
            k_view = k_c[tables].reshape(slots, max_len, kh, hd)
            v_view = v_c[tables].reshape(slots, max_len, kh, hd)
            y, k_new, v_new = gen_lib._layer_verify_read_only(
                config, pl, carry, positions, k_view, v_view, lengths
            )
            return y, (k_new, v_new)

        x, (k_news, v_news) = jax.lax.scan(
            body, x, (params["layers"], k, v)
        )
        blk, off = _verify_coords(tables, lengths, active)
        k = k.at[:, blk, off].set(k_news.astype(k.dtype))
        v = v.at[:, blk, off].set(v_news.astype(v.dtype))
        logits = llama.unembed(config, params, x)        # [slots, T, V]
        emitted, acc = spec_lib.spec_accept(
            logits, drafts, draft_len, temps, active, tokens,
            rng, step_idx,
        )
        return k, v, emitted, acc

    def verify_q8(k, v, ks, vs, params, tables, lengths, tokens,
                  drafts, draft_len, active, temps, rng, step_idx):
        counts["verify"] += 1  # traces only
        toks = jnp.concatenate([tokens[:, None], drafts], axis=1)
        positions = (
            lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        )
        x = llama.embed_tokens(config, params, toks)

        def body(carry, layer_in):
            pl, k_c, v_c, ks_c, vs_c = layer_in
            k_view = k_c[tables].reshape(slots, max_len, kh, hd)
            v_view = v_c[tables].reshape(slots, max_len, kh, hd)
            ks_view = ks_c[tables].reshape(slots, max_len, kh)
            vs_view = vs_c[tables].reshape(slots, max_len, kh)
            y, kq, ks_rows, vq, vs_rows = (
                gen_lib._layer_verify_read_only(
                    config, pl, carry, positions, k_view, v_view,
                    lengths, k_scale=ks_view, v_scale=vs_view,
                )
            )
            return y, (kq, ks_rows, vq, vs_rows)

        x, (kqs, ks_news, vqs, vs_news) = jax.lax.scan(
            body, x, (params["layers"], k, v, ks, vs)
        )
        blk, off = _verify_coords(tables, lengths, active)
        k = k.at[:, blk, off].set(kqs)
        v = v.at[:, blk, off].set(vqs)
        ks = ks.at[:, blk, off].set(ks_news)
        vs = vs.at[:, blk, off].set(vs_news)
        logits = llama.unembed(config, params, x)
        emitted, acc = spec_lib.spec_accept(
            logits, drafts, draft_len, temps, active, tokens,
            rng, step_idx,
        )
        return k, v, ks, vs, emitted, acc

    return verify_q8 if quantized else verify


def _build_paged_draft(config, slots: int, max_blocks: int,
                       block_size: int, K: int, draft_layers: int,
                       counts, quantized: bool = False):
    """Paged early-exit drafter: K sequential single-token partial
    forwards (first ``draft_layers`` blocks) through the block-table
    gather; each drafted row's partial-layer K/V is appended beyond
    the fill (sentinel-redirected when invalid) so the next draft can
    attend it. The verify pass rewrites all layers of those rows
    before any can become visible."""
    max_len = max_blocks * block_size
    kh, hd = config.n_kv_heads, config.head_dim
    d = draft_layers

    def _coords(tables, lens_i, active):
        valid = active & (lens_i < max_len)
        w = jnp.minimum(lens_i, max_len - 1)
        blk = jnp.take_along_axis(
            tables, (w // block_size)[:, None], axis=1
        )[:, 0]
        blk = jnp.where(valid, blk, SENTINEL_BLOCK)
        off = jnp.where(valid, w % block_size, 0)
        return blk, off

    def draft(k, v, params, tables, lengths, tokens, active):
        counts["draft"] += 1  # traces only
        layers_d = jax.tree_util.tree_map(
            lambda a: a[:d], params["layers"]
        )
        cur = tokens
        drafts = []
        for i in range(K):
            lens_i = lengths + i
            positions = lens_i[:, None]
            x = llama.embed_tokens(config, params, cur[:, None])

            def body(carry, layer_in):
                pl, k_c, v_c = layer_in
                k_view = k_c[tables].reshape(slots, max_len, kh, hd)
                v_view = v_c[tables].reshape(slots, max_len, kh, hd)
                y, k_new, v_new = gen_lib._layer_decode_read_only(
                    config, pl, carry, positions, k_view, v_view,
                    lens_i,
                )
                return y, (k_new, v_new)

            x, (k_news, v_news) = jax.lax.scan(
                body, x, (layers_d, k[:d], v[:d])
            )
            blk, off = _coords(tables, lens_i, active)
            k = k.at[:d, blk, off].set(k_news[:, :, 0].astype(k.dtype))
            v = v.at[:d, blk, off].set(v_news[:, :, 0].astype(v.dtype))
            logits = llama.unembed(config, params, x)[:, 0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            cur = jnp.where(active, nxt, cur)
            drafts.append(cur)
        return k, v, jnp.stack(drafts, axis=1)

    def draft_q8(k, v, ks, vs, params, tables, lengths, tokens,
                 active):
        from dlrover_tpu.ops.kv_quant import quantize_kv

        counts["draft"] += 1  # traces only
        layers_d = jax.tree_util.tree_map(
            lambda a: a[:d], params["layers"]
        )
        cur = tokens
        drafts = []
        for i in range(K):
            lens_i = lengths + i
            positions = lens_i[:, None]
            x = llama.embed_tokens(config, params, cur[:, None])

            def body(carry, layer_in):
                pl, k_c, v_c, ks_c, vs_c = layer_in
                k_view = k_c[tables].reshape(slots, max_len, kh, hd)
                v_view = v_c[tables].reshape(slots, max_len, kh, hd)
                ks_view = ks_c[tables].reshape(slots, max_len, kh)
                vs_view = vs_c[tables].reshape(slots, max_len, kh)
                y, k_new, v_new = gen_lib._layer_decode_read_only(
                    config, pl, carry, positions, k_view, v_view,
                    lens_i, k_scale=ks_view, v_scale=vs_view,
                )
                return y, (k_new, v_new)

            x, (k_news, v_news) = jax.lax.scan(
                body, x, (layers_d, k[:d], v[:d], ks[:d], vs[:d])
            )
            blk, off = _coords(tables, lens_i, active)
            kq, ks_rows = quantize_kv(k_news[:, :, 0])
            vq, vs_rows = quantize_kv(v_news[:, :, 0])
            k = k.at[:d, blk, off].set(kq)
            v = v.at[:d, blk, off].set(vq)
            ks = ks.at[:d, blk, off].set(ks_rows)
            vs = vs.at[:d, blk, off].set(vs_rows)
            logits = llama.unembed(config, params, x)[:, 0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            cur = jnp.where(active, nxt, cur)
            drafts.append(cur)
        return k, v, ks, vs, jnp.stack(drafts, axis=1)

    return draft_q8 if quantized else draft


@functools.lru_cache(maxsize=16)
def _paged_spec_steps(
    config: llama.TpuLMConfig, slots: int, num_blocks: int,
    max_blocks: int, block_size: int, spec_k: int, draft_layers: int,
    kv_dtype: str = "fp",
) -> _PagedSpecSteps:
    counts = {"verify": 0, "draft": 0}
    quantized = kv_dtype == "int8"
    pool_args = (0, 1, 2, 3) if quantized else (0, 1)
    verify = jax.jit(
        _build_paged_verify(config, slots, max_blocks, block_size,
                            spec_k, counts, quantized=quantized),
        donate_argnums=pool_args,
    )
    draft = None
    if draft_layers > 0:
        draft = jax.jit(
            _build_paged_draft(config, slots, max_blocks, block_size,
                               spec_k, draft_layers, counts,
                               quantized=quantized),
            donate_argnums=pool_args,
        )
    return _PagedSpecSteps(verify=verify, draft=draft,
                           trace_counts=counts)


@functools.lru_cache(maxsize=16)
def _paged_steps(
    config: llama.TpuLMConfig, slots: int, num_blocks: int,
    max_blocks: int, block_size: int, chunk: int,
    kv_dtype: str = "fp",
) -> _PagedSteps:
    """Compiled once per shape key, shared across engines (the flat
    engine's lru_cache discipline). Pools donated; tables/lengths/ids
    all plain traced arguments. ``kv_dtype`` "int8" programs also
    donate the scale pools."""
    counts = {"prefill": 0, "decode": 0, "cow": 0, "imp": 0, "exp": 0}
    quantized = kv_dtype == "int8"
    pool_args = (0, 1, 2, 3) if quantized else (0, 1)
    decode = jax.jit(
        _build_paged_decode(config, slots, max_blocks, block_size,
                            counts, quantized=quantized),
        donate_argnums=pool_args,
    )
    prefill = jax.jit(
        _build_paged_prefill(config, max_blocks, block_size, chunk,
                             counts, quantized=quantized),
        donate_argnums=pool_args,
    )
    cow = jax.jit(
        _build_cow_copy(counts, quantized=quantized),
        donate_argnums=pool_args,
    )
    imp = jax.jit(
        _build_import_scatter(counts, quantized=quantized),
        donate_argnums=pool_args,
    )
    # No donation: export reads the pools and the source keeps serving
    # from them until the importer acks.
    exp = jax.jit(_build_export_gather(counts, quantized=quantized))
    return _PagedSteps(prefill=prefill, decode=decode, cow=cow,
                       imp=imp, exp=exp, trace_counts=counts)


class PagedServingEngine(ServingEngine):
    """ServingEngine over a paged block pool (see module docstring).

    Same host-side step loop, scheduler, metrics, spans, and recovery
    semantics as the flat engine — only the pool hooks and the two step
    programs differ. ``num_blocks`` defaults to exactly the flat
    engine's HBM budget (``slots * max_len / block_size`` + sentinel);
    pass fewer blocks and MORE slots for the oversubscribed capacity
    win the bench measures. ``kv_cache_dtype="int8"`` stores the pool
    as int8 with per-(row, head) f32 scale pools (ops/kv_quant, §33):
    ~1.94x the blocks fit the same HBM, dequantization folds into the
    attention math, and COW/prefix/preemption machinery is unchanged
    (shared blocks share their scales)."""

    def __init__(
        self,
        config: llama.TpuLMConfig,
        params,
        slots: int,
        max_len: int,
        prefill_chunk: int = 64,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        prefix_cache: bool = True,
        prefix_cache_blocks: Optional[int] = None,
        token_budget: Optional[int] = None,
        drain_mode: bool = False,
        rng=None,
        registry=None,
        max_requeues: int = 3,
        slo_classes=None,
        kv_cache_dtype: str = "fp",
        spec_k: int = 0,
        spec_drafter: str = "ngram",
        spec_draft_layers: int = 2,
    ):
        if kv_cache_dtype not in ("fp", "int8"):
            raise ValueError(
                f"kv_cache_dtype {kv_cache_dtype!r} not in "
                f"('fp', 'int8')"
            )
        if max_len % block_size:
            raise ValueError(
                f"max_len {max_len} must be a multiple of block_size "
                f"{block_size}"
            )
        if prefill_chunk % block_size and block_size % prefill_chunk:
            # Chunk/block alignment keeps the prefill scatter-back a
            # STATIC number of whole blocks; misaligned chunks would
            # straddle a shared/fresh block boundary mid-block.
            raise ValueError(
                f"prefill_chunk {prefill_chunk} and block_size "
                f"{block_size} must divide one another"
            )
        self.kv_cache_dtype = kv_cache_dtype
        self.block_size = block_size
        self.max_blocks = max_len // block_size
        if num_blocks is None:
            num_blocks = slots * self.max_blocks + 1
        if num_blocks - 1 < self.max_blocks:
            # Room for at least one full-length slot, or pool-pressure
            # relief (evict cache, preempt peers) could never free
            # enough for a lone max-length request.
            raise ValueError(
                f"num_blocks {num_blocks} cannot hold one full slot "
                f"({self.max_blocks} blocks + sentinel)"
            )
        self.num_blocks = num_blocks
        self._allocator = BlockAllocator(num_blocks, reserved=1)
        self._cache: Optional[PrefixCache] = (
            PrefixCache(self._allocator, block_size,
                        capacity_blocks=prefix_cache_blocks)
            if prefix_cache else None
        )
        self._tables = np.zeros(
            (slots, self.max_blocks), np.int32
        )
        self._slot_blocks: List[List[int]] = [[] for _ in range(slots)]
        # USABLE-hit accounting (what kv_stats/bench/heartbeats report):
        # a raw cache hit whose blocks are all discarded by chunk
        # alignment saved nothing and must count as a miss — the
        # cache's own raw counters would overstate the win.
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_hit_blocks = 0
        # The base __init__ builds the value pools via _fresh_pool();
        # the int8 scale pools pair up right after it returns (nothing
        # in between touches them).
        super().__init__(
            config, params, slots, max_len,
            prefill_chunk=prefill_chunk, token_budget=token_budget,
            drain_mode=drain_mode, rng=rng, registry=registry,
            max_requeues=max_requeues, slo_classes=slo_classes,
            spec_k=spec_k, spec_drafter=spec_drafter,
            spec_draft_layers=spec_draft_layers,
        )
        self._kscale, self._vscale = self._fresh_scales()
        # Block watermark: only admit a request the pool can hold
        # (prompt + first decode block) counting evictable cache as
        # free — otherwise bursty arrivals thrash preemptions, each
        # one burning its victim's whole prefill investment.
        self.scheduler.admission_gate = self._can_admit
        # The base __init__ bound the FLAT step programs (never traced
        # — jit is lazy); swap in the paged programs, keyed on the
        # paged shapes, and re-settle the retrace snapshot.
        self._steps = _paged_steps(
            config, slots, self.num_blocks, self.max_blocks,
            block_size, prefill_chunk, kv_dtype=kv_cache_dtype,
        )
        if self.spec_k:
            # Same swap for the spec programs (the flat ones the base
            # __init__ bound were never traced — jit is lazy).
            self._spec = _paged_spec_steps(
                config, slots, self.num_blocks, self.max_blocks,
                block_size, self.spec_k, self.spec_draft_layers,
                kv_dtype=kv_cache_dtype,
            )
        self._trace_snapshot = self._all_trace_counts()
        # K+V bytes per block, for the HBM-in-use gauge: int8 pools
        # pay 1 byte/element + one f32 scale per (row, head) — the
        # 1.94x-per-token capacity lever the equal-HBM bench exploits.
        from dlrover_tpu.ops.kv_quant import bytes_per_head_row

        self._block_bytes = int(
            2 * config.n_layers * block_size * config.n_kv_heads
            * bytes_per_head_row(
                config.head_dim, kv_cache_dtype,
                jnp.dtype(config.compute_dtype).itemsize,
            )
        )
        self.metrics.kv_blocks_total.set(self._allocator.managed)

    # ---- pool construction / programs --------------------------------------

    @property
    def _quantized(self) -> bool:
        return self.kv_cache_dtype == "int8"

    def _fresh_pool(self):
        shape = (
            self.config.n_layers, self.num_blocks, self.block_size,
            self.config.n_kv_heads, self.config.head_dim,
        )
        if self._quantized:
            return jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8)
        return (
            jnp.zeros(shape, self.config.compute_dtype),
            jnp.zeros(shape, self.config.compute_dtype),
        )

    def _pools(self):
        """The donated-pool argument tuple every compiled program
        leads with: (k, v) for fp, (k, v, k_scale, v_scale) for int8.
        Call sites splat this and hand the returned tuple back to
        :meth:`_set_pools` — ONE argument list per program, whatever
        the dtype."""
        if self._quantized:
            return (self._k, self._v, self._kscale, self._vscale)
        return (self._k, self._v)

    def _set_pools(self, pools) -> None:
        if self._quantized:
            self._k, self._v, self._kscale, self._vscale = pools
        else:
            self._k, self._v = pools

    def _fresh_scales(self):
        """(k_scale, v_scale) pools for the int8 cache — (None, None)
        for fp. Every value-pool rebuild site (init, warmup,
        step-error recovery) pairs a _fresh_pool() call with this one
        so value and scale pools can never be mismatched."""
        if not self._quantized:
            return None, None
        shape = (
            self.config.n_layers, self.num_blocks, self.block_size,
            self.config.n_kv_heads,
        )
        return (
            jnp.zeros(shape, jnp.float32),
            jnp.zeros(shape, jnp.float32),
        )

    def warmup(self) -> None:
        """Compile all three paged programs on throwaway state, then
        rebuild the pool — first real request pays no compile."""
        chunk = np.zeros((1, self.prefill_chunk), np.int32)
        pools = self._pools()
        *pools, first = self._steps.prefill(
            *pools, self._params, jnp.asarray(chunk),
            jnp.zeros(self.max_blocks, jnp.int32),
            np.int32(0), np.int32(1), np.float32(0.0),
            self._rng, np.int32(0),
        )
        *pools, nxt = self._steps.decode(
            *pools, self._params,
            jnp.asarray(np.zeros((self.slots, self.max_blocks),
                                 np.int32)),
            jnp.asarray(np.zeros(self.slots, np.int32)),
            jnp.asarray(np.zeros(self.slots, np.int32)),
            jnp.asarray(np.zeros(self.slots, bool)),
            jnp.asarray(np.zeros(self.slots, np.float32)),
            self._rng, np.int32(0),
        )
        pools = self._steps.cow(*pools, np.int32(0), np.int32(0))
        blk_shape = (
            self.config.n_layers, self.block_size,
            self.config.n_kv_heads, self.config.head_dim,
        )
        if self._quantized:
            z8 = jnp.zeros(blk_shape, jnp.int8)
            zs = jnp.zeros(blk_shape[:-1], jnp.float32)
            pools = self._steps.imp(
                *pools, z8, z8, zs, zs, np.int32(0)
            )
        else:
            # Import hands dequantized f32 host rows (kvpool/migrate).
            zf = jnp.zeros(blk_shape, jnp.float32)
            pools = self._steps.imp(*pools, zf, zf, np.int32(0))
        # Export gather (non-donating): warm so the first migration
        # out of this engine never stalls the serve loop on a compile.
        jax.block_until_ready(self._steps.exp(*pools, np.int32(0)))
        if self._spec is not None:
            tbl = jnp.asarray(
                np.zeros((self.slots, self.max_blocks), np.int32)
            )
            z_i = jnp.asarray(np.zeros(self.slots, np.int32))
            z_b = jnp.asarray(np.zeros(self.slots, bool))
            z_f = jnp.asarray(np.zeros(self.slots, np.float32))
            drafts = jnp.asarray(
                np.zeros((self.slots, self.spec_k), np.int32)
            )
            if self._spec.draft is not None:
                *pools, drafts = self._spec.draft(
                    *pools, self._params, tbl, z_i, z_i, z_b
                )
            *pools, _em, acc = self._spec.verify(
                *pools, self._params, tbl, z_i, z_i, drafts, z_i,
                z_b, z_f, self._rng, np.int32(0),
            )
            jax.block_until_ready(acc)
        jax.block_until_ready(pools[-1])
        del pools
        self._k, self._v = self._fresh_pool()
        self._kscale, self._vscale = self._fresh_scales()
        self._trace_snapshot = self._all_trace_counts()

    # ---- block bookkeeping -------------------------------------------------

    def _can_admit(self, req: Request) -> bool:
        """Admission watermark: free + cache-evictable blocks must
        cover the request's whole prompt plus one decode block (a
        prefix hit only LOWERS the real need — conservative). A
        request that already LOST its slot to pool pressure re-admits
        pessimistically, against its full prompt+decode worst case:
        optimistic re-admission is exactly the preempt-readmit-preempt
        thrash cycle, each lap burning a whole prefill."""
        rows = req.prompt_len + (
            req.max_new_tokens if req.preemptions else 1
        )
        need = -(-min(rows, self.max_len) // self.block_size)
        stats = self._allocator.stats(self._live_block_ids())
        return stats["free"] + stats["cached"] >= need

    def _live_block_ids(self) -> set:
        live = set()
        for blocks in self._slot_blocks:
            live.update(blocks)
        return live

    def _alloc_blocks(self, n: int, requester: Request) -> List[int]:
        """All-or-nothing allocation with the relief ladder: prefix
        cache LRU eviction first, then preemption of the YOUNGEST
        active request (never ``requester``). Raises only when relief
        is structurally impossible (requester alone overflows the
        pool), which the step-error recovery path bounds."""
        while True:
            try:
                return self._allocator.alloc(n)
            except BlockPoolExhausted:
                missing = n - self._allocator.free_count()
                if self._cache is not None and self._cache.evict_lru(
                    missing
                ):
                    continue
                victim = self._pick_preemption_victim(requester)
                if victim is None:
                    raise
                self._preempt(victim)

    def _pick_preemption_victim(
        self, requester: Request
    ) -> Optional[Request]:
        cands = [
            r for r in self.scheduler.active()
            if r is not requester and r.state in (PREFILL, DECODE)
            and self._slot_blocks[r.slot]
        ]
        if not cands:
            return None
        return max(cands, key=lambda r: r.rid)  # youngest first out

    def _preempt(self, victim: Request) -> None:
        slot = victim.slot
        # Reset-to-zero progress is wasted compute (§34 accounting).
        if victim.prefill_pos:
            self.metrics.tokens_wasted.inc(
                victim.prefill_pos, kind="prefill"
            )
        if victim.tokens:
            self.metrics.tokens_wasted.inc(
                len(victim.tokens), kind="decode"
            )
        self.scheduler.preempt(victim)
        self._release_slot(victim, slot)
        self._lengths[slot] = 0
        self._tokens[slot] = 0
        self._temps[slot] = 0.0
        self.metrics.kv_preemptions.inc()
        self.metrics.requests.inc(outcome="preempted")
        self.metrics.annotate(
            "serving_preempt", rid=victim.rid, slot=slot,
        )
        logger.info(
            "kvpool pressure: preempted rid %d (slot %d) to free "
            "blocks", victim.rid, slot,
        )

    def _ensure_blocks(self, req: Request, upto_rows: int) -> None:
        """Grow ``req``'s block table to cover ``upto_rows`` logical
        rows (clamped to max_len)."""
        upto_rows = min(upto_rows, self.max_len)
        need = -(-upto_rows // self.block_size)
        blocks = self._slot_blocks[req.slot]
        missing = need - len(blocks)
        if missing <= 0:
            return
        fresh = self._alloc_blocks(missing, req)
        start = len(blocks)
        blocks.extend(fresh)
        self._tables[req.slot, start:start + len(fresh)] = fresh

    def _privatize(self, req: Request, logical_idx: int) -> None:
        """COW: the slot is about to WRITE logical block
        ``logical_idx``; if that block is shared, copy it to a fresh
        private block first (shared blocks are immutable)."""
        blocks = self._slot_blocks[req.slot]
        old = blocks[logical_idx]
        if self._allocator.refcount(old) <= 1:
            return
        new = self._alloc_blocks(1, req)[0]
        self._set_pools(self._steps.cow(
            *self._pools(), np.int32(old), np.int32(new)
        ))
        self._allocator.decref(old)
        self._allocator.cow_copies_total += 1
        blocks[logical_idx] = new
        self._tables[req.slot, logical_idx] = new
        self.metrics.kv_cow_copies.inc()

    # ---- pool hooks (the base step loop calls these) -----------------------

    def _admit_slot(self, req: Request) -> None:
        super()._admit_slot(req)
        slot = req.slot
        self._tables[slot, :] = SENTINEL_BLOCK
        self._slot_blocks[slot] = []
        if self._cache is None:
            return
        hit = self._cache.lookup(req.prompt)
        # Never skip the FINAL prompt token: its forward produces the
        # first sampled token, so a full-prompt hit still re-runs the
        # last chunk (identical values; COW privatizes any shared
        # touched block). Chunk-align the resume point, and drop hit
        # blocks that lie ENTIRELY inside the re-prefilled span — they
        # would only be COW-copied and rewritten.
        start = 0
        if hit:
            start = min(len(hit) * self.block_size, req.prompt_len - 1)
            start -= start % self.prefill_chunk
            keep = -(-start // self.block_size)  # partial head stays
            for block in hit[keep:]:
                self._allocator.decref(block)
            hit = hit[:keep]
        if not hit:
            self.metrics.prefix_lookups.inc(outcome="miss")
            self._prefix_misses += 1
            return
        self.metrics.prefix_lookups.inc(outcome="hit")
        self.metrics.prefix_hit_blocks.inc(len(hit))
        self._prefix_hits += 1
        self._prefix_hit_blocks += len(hit)
        req.prefix_hit_blocks = len(hit)
        self._slot_blocks[slot] = list(hit)
        self._tables[slot, :len(hit)] = hit
        req.prefill_pos = start
        self._lengths[slot] = start
        self.metrics.annotate(
            "serving_prefix_hit", rid=req.rid, blocks=len(hit),
            resumed_at=start,
        )

    def _release_slot(self, req: Request, slot: int) -> None:
        for block in self._slot_blocks[slot]:
            self._allocator.decref(block)
        self._slot_blocks[slot] = []
        self._tables[slot, :] = SENTINEL_BLOCK

    def _reset_pool(self) -> None:
        # A failed step may have invalidated the donated pools: the
        # device blocks AND everything that points at them (allocator,
        # prefix cache, tables, int8 scale pools) restart from scratch.
        self._k, self._v = self._fresh_pool()
        self._kscale, self._vscale = self._fresh_scales()
        self._allocator = BlockAllocator(self.num_blocks, reserved=1)
        if self._cache is not None:
            self._cache = PrefixCache(
                self._allocator, self.block_size,
                capacity_blocks=self._cache.capacity_blocks,
            )
        self._tables[:, :] = SENTINEL_BLOCK
        self._slot_blocks = [[] for _ in range(self.slots)]

    def _sync_pool_metrics(self) -> None:
        stats = self._allocator.stats(self._live_block_ids())
        self.metrics.kv_blocks.set(stats["free"], state="free")
        self.metrics.kv_blocks.set(stats["used"], state="used")
        self.metrics.kv_blocks.set(stats["cached"], state="cached")
        self.metrics.kv_bytes_in_use.set(
            (stats["used"] + stats["cached"]) * self._block_bytes
        )

    # ---- step internals ----------------------------------------------------

    def _run_prefill_chunk(self, req: Request, finished: List[Request]):
        c = self.prefill_chunk
        start = req.prefill_pos
        n_valid = min(c, req.prompt_len - start)
        self._ensure_blocks(req, start + n_valid)
        # Privatize every block this chunk touches (a prefix-hit resume
        # can chunk-align BELOW the shared span: the re-prefill writes
        # identical values, but never into a shared block).
        first_blk = start // self.block_size
        last_blk = min(
            (start + c - 1) // self.block_size,
            len(self._slot_blocks[req.slot]) - 1,
        )
        for idx in range(first_blk, last_blk + 1):
            self._privatize(req, idx)
        chunk = np.zeros((1, c), np.int32)
        chunk[0, :n_valid] = req.prompt[start:start + n_valid]
        *pools, first = self._steps.prefill(
            *self._pools(), self._params, jnp.asarray(chunk),
            jnp.asarray(self._tables[req.slot]),
            np.int32(start), np.int32(n_valid),
            np.float32(req.temperature), self._rng,
            np.int32(self._step_idx),
        )
        self._set_pools(pools)
        req.prefill_pos += n_valid
        self._lengths[req.slot] = req.prefill_pos
        self.metrics.tokens.inc(n_valid, kind="prefill")
        if req.prefill_pos < req.prompt_len:
            return
        if self._cache is not None:
            # Register the FULL prompt blocks for future hits (partial
            # tails stay private: the owner's decode appends into them).
            n_full = req.prompt_len // self.block_size
            self._cache.insert(
                req.prompt, self._slot_blocks[req.slot][:n_full]
            )
        tok = int(jax.device_get(first))
        req.first_token_ts = time.monotonic()
        if req.requeues == 0:
            self.metrics.ttft.observe(req.ttft_s)
        req.tokens.append(tok)
        self._tokens[req.slot] = tok
        self.metrics.tokens.inc(kind="decode")
        if len(req.tokens) >= req.max_new_tokens:
            self._finish(req, finished)
        else:
            req.state = DECODE

    def _run_decode(self, decoding: List[Request],
                    finished: List[Request]):
        if self.spec_k:
            self._run_decode_spec(decoding, finished)
            return
        # Block-budget pass FIRST: growing a cursor past a block edge
        # may preempt the youngest peer, which must then sit this
        # iteration out.
        for r in list(decoding):
            if r.state != DECODE:
                continue  # preempted by an earlier peer's allocation
            cursor = min(self._lengths[r.slot], self.max_len - 1)
            self._ensure_blocks(r, cursor + 1)
            self._privatize(r, cursor // self.block_size)
        decoding = [r for r in decoding if r.state == DECODE]
        if not decoding:
            return
        active = np.zeros(self.slots, bool)
        for r in decoding:
            active[r.slot] = True
        *pools, nxt = self._steps.decode(
            *self._pools(), self._params, jnp.asarray(self._tables),
            jnp.asarray(self._lengths), jnp.asarray(self._tokens),
            jnp.asarray(active), jnp.asarray(self._temps),
            self._rng, np.int32(self._step_idx),
        )
        self._set_pools(pools)
        nxt = np.asarray(jax.device_get(nxt))
        for r in decoding:
            self._lengths[r.slot] += 1
            tok = int(nxt[r.slot])
            r.tokens.append(tok)
            self._tokens[r.slot] = tok
            self.metrics.tokens.inc(kind="decode")
            self._iter_advance.append(1)
            if len(r.tokens) >= r.max_new_tokens:
                self._finish(r, finished)
            elif self._lengths[r.slot] + 1 > self.max_len:
                r.truncated = True
                self._finish(r, finished)

    # ---- speculative decode hooks (§35) ------------------------------------

    def _spec_prepare_rows(self, decoding: List[Request]):
        """Every decoding slot needs rows fill..fill+spec_k writable
        BEFORE the device calls: allocate the covering blocks (relief
        ladder may preempt the youngest peer, which then sits this
        iteration out) and privatize every touched block — drafted-
        then-rejected rows must never land in a block another slot or
        the prefix cache shares."""
        T = self.spec_k + 1
        for r in list(decoding):
            if r.state != DECODE:
                continue  # preempted by an earlier peer's allocation
            fill = int(self._lengths[r.slot])
            upto = min(fill + T, self.max_len)
            self._ensure_blocks(r, upto)
            first_blk = min(fill, self.max_len - 1) // self.block_size
            last_blk = min(
                (upto - 1) // self.block_size,
                len(self._slot_blocks[r.slot]) - 1,
            )
            for idx in range(first_blk, last_blk + 1):
                self._privatize(r, idx)
        return [r for r in decoding if r.state == DECODE]

    def _spec_draft_device(self, active):
        *pools, drafts = self._spec.draft(
            *self._pools(), self._params, jnp.asarray(self._tables),
            jnp.asarray(self._lengths), jnp.asarray(self._tokens),
            jnp.asarray(active),
        )
        self._set_pools(pools)
        return drafts

    def _spec_verify_device(self, active, drafts, draft_len):
        *pools, emitted, acc = self._spec.verify(
            *self._pools(), self._params, jnp.asarray(self._tables),
            jnp.asarray(self._lengths), jnp.asarray(self._tokens),
            jnp.asarray(drafts), jnp.asarray(draft_len),
            jnp.asarray(active), jnp.asarray(self._temps),
            self._rng, np.int32(self._step_idx),
        )
        self._set_pools(pools)
        return emitted, acc

    # ---- observability -----------------------------------------------------

    def kv_stats(self) -> Dict[str, object]:
        """Allocator + prefix-cache accounting (heartbeats, SignalBus,
        bench, the chaos block-reclaim invariant)."""
        stats = dict(self._allocator.stats(self._live_block_ids()))
        stats["bytes_in_use"] = (
            (stats["used"] + stats["cached"]) * self._block_bytes
        )
        stats["cow_copies"] = self._allocator.cow_copies_total
        if self._cache is not None:
            for key, value in self._cache.stats().items():
                stats[f"prefix_{key}"] = value
            # Report USABLE hits (blocks that actually skipped
            # prefill), not the cache's raw lookup counters: a hit
            # fully discarded by chunk alignment saved nothing.
            lookups = self._prefix_hits + self._prefix_misses
            stats["prefix_hits"] = self._prefix_hits
            stats["prefix_misses"] = self._prefix_misses
            stats["prefix_hit_blocks"] = self._prefix_hit_blocks
            stats["prefix_hit_rate"] = round(
                self._prefix_hits / lookups if lookups else 0.0, 4
            )
        return stats

    def check_block_invariants(self) -> None:
        """Raise unless conservation + refcount sanity hold (tests)."""
        self._allocator.check()
        stats = self._allocator.stats(self._live_block_ids())
        total = stats["free"] + stats["used"] + stats["cached"]
        if total != self._allocator.managed:
            raise AssertionError(
                f"free+used+cached {total} != managed "
                f"{self._allocator.managed}: {stats}"
            )
