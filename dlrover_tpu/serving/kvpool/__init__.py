"""Paged KV memory plane (§31): block-table cache, cross-request
prefix reuse, SLO-class admission riding the serving scheduler.

- :class:`BlockAllocator` — jax-free free list + refcounts + COW over
  the ``[layers, num_blocks, block_size, kv_heads, head_dim]`` pool;
- :class:`PrefixCache` — token-prefix trie → warm refcounted block
  chains, leaf-first LRU eviction;
- :class:`PagedServingEngine` — the flat engine's step loop over block
  tables threaded as traced args (zero retraces across admissions),
  prefix-hit prefill skipping, pool-pressure relief (cache eviction →
  youngest-request preemption);
- ``migrate`` (§36) — a request's blocks + scheduler state as bytes:
  export from one engine, decode-entry import into another (the
  disaggregated-serving / live-drain primitive).
"""

from dlrover_tpu.serving.kvpool.allocator import (
    BlockAllocator,
    BlockPoolExhausted,
)
from dlrover_tpu.serving.kvpool.engine import (
    SENTINEL_BLOCK,
    PagedServingEngine,
)
from dlrover_tpu.serving.kvpool.migrate import (
    MigrationError,
    MigrationRefused,
    can_import,
    export_request,
    import_request,
    peek_header,
    release_exported,
)
from dlrover_tpu.serving.kvpool.prefix_cache import PrefixCache

__all__ = [
    "BlockAllocator",
    "BlockPoolExhausted",
    "PrefixCache",
    "PagedServingEngine",
    "SENTINEL_BLOCK",
    "MigrationError",
    "MigrationRefused",
    "can_import",
    "export_request",
    "import_request",
    "peek_header",
    "release_exported",
]
