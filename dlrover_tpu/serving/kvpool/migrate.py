"""Block migration: a request's paged-KV state as bytes (§36).

The disaggregated serving plane treats paged KV blocks as a fleet-wide
currency: a prefill replica fills a request's blocks, serializes them,
and a decode replica admits the request MID-STREAM — no re-prefill.
The same primitive backs live drain (autoscaler shrink / weight swap
without killing in-flight decodes).

- :func:`export_request` serializes a DECODE-state request on a source
  :class:`PagedServingEngine`: block contents (always int8 on the wire
  via ``ops.kv_quant.kv_to_wire`` — bit-exact passthrough for int8
  caches, quantize-on-export for fp caches so the wire cost roughly
  halves), fill cursor, sampled tokens, and scheduler state. The
  source keeps the request live until the importer acks — the caller
  decides whether the fallback is source-side completion (live drain)
  or a from-scratch re-prefill (two-phase dispatch).
- :func:`release_exported` drops the request from the source after the
  ack: slot recycled, blocks decref'd — conservation holds (prompt
  blocks the prefix cache holds a ref on stay cached).
- :func:`import_request` admits the payload into a destination engine
  through the scheduler's DECODE-entry path: allocate blocks (fresh,
  refcount 1 — COW state is rebuilt by construction, never shipped),
  install the table and fill, scatter the rows through a compiled
  per-block program whose destination id is a traced scalar (zero
  retraces, the COW-copy discipline), register the full prompt blocks
  into the destination prefix trie (hit-rate survives migration), and
  reconstruct the request's phase timeline on the local monotonic
  clock — the ``serving.migrate`` span lands between the (source-side)
  prefill and the local decode.

Payload layout: ``MAGIC | u32 header_len | json header | kv wire``
with the kv wire from :func:`ops.kv_quant.kv_to_wire` (its own
self-describing header carries dtype + shapes). Wall-clock export
stamps bound the migration pause across processes on one host.
"""

import json
import struct
import time
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import logger
from dlrover_tpu.ops.kv_quant import kv_from_wire, kv_to_wire
from dlrover_tpu.serving.scheduler import DECODE, Request

MIGRATE_MAGIC = b"KVM1"


class MigrationError(RuntimeError):
    """Structural failure: payload malformed or engines incompatible."""


class MigrationRefused(MigrationError):
    """Destination cannot hold the request right now (no free slot /
    not enough blocks) — the router treats this as a routing miss, not
    a breaker strike."""


def export_request(engine, req: Request,
                   now: Optional[float] = None) -> bytes:
    """Serialize ``req``'s blocks + scheduler state on the source
    engine. The request stays LIVE on the source — pair with
    :func:`release_exported` once the importer acks."""
    if req.state != DECODE or not req.tokens:
        raise MigrationError(
            f"rid {req.rid} not migratable: state={req.state!r}, "
            f"{len(req.tokens)} tokens (prefill must have completed)"
        )
    if req.slot < 0:
        raise MigrationError(f"rid {req.rid} holds no slot")
    if now is None:
        now = time.monotonic()
    slot = req.slot
    blocks = list(engine._slot_blocks[slot])
    fill = int(engine._lengths[slot])
    if not blocks or fill > len(blocks) * engine.block_size:
        raise MigrationError(
            f"rid {req.rid}: fill {fill} exceeds {len(blocks)} blocks"
        )
    # Per-block compiled gather (``exp`` in _PagedSteps), NOT a jnp
    # fancy-index: ``k[:, ids]`` specializes XLA on len(ids), so every
    # distinct block count a migration touched compiled a fresh gather
    # (~400ms each on CPU) INSIDE the source's serve loop — the decode
    # batch stalled exactly when a request was leaving to unblock it.
    # n calls of one warmed program trade that for n dispatches.
    rows = [
        jax.device_get(engine._steps.exp(*engine._pools(), np.int32(b)))
        for b in blocks
    ]
    k_rows = np.stack([r[0] for r in rows], axis=1)
    v_rows = np.stack([r[1] for r in rows], axis=1)
    if engine._quantized:
        wire = kv_to_wire(
            k_rows, v_rows,
            k_scale=np.stack([r[2] for r in rows], axis=1),
            v_scale=np.stack([r[3] for r in rows], axis=1),
        )
    else:
        wire = kv_to_wire(k_rows, v_rows)
    admit_ts = req.admit_ts if req.admit_ts is not None else (
        req.submit_ts
    )
    first_ts = req.first_token_ts if req.first_token_ts is not None \
        else now
    header = {
        "v": 1,
        "src_rid": req.rid,
        "prompt": [int(t) for t in req.prompt],
        "tokens": [int(t) for t in req.tokens],
        "max_new_tokens": req.max_new_tokens,
        "temperature": req.temperature,
        "slo_class": req.slo_class,
        "fill": fill,
        "n_blocks": len(blocks),
        "block_size": engine.block_size,
        "src_kv_dtype": engine.kv_cache_dtype,
        # Source-side phase durations, for timeline reconstruction on
        # the destination clock (monotonic stamps don't cross
        # processes; durations do).
        "queue_s": max(admit_ts - req.submit_ts, 0.0),
        "prefill_s": max(first_ts - admit_ts, 0.0),
        "decode_s": max(now - first_ts, 0.0),
        "deadline_remaining_s": (
            req.deadline - now if req.deadline is not None else None
        ),
        "prefix_hit_blocks": req.prefix_hit_blocks,
        # Wall clock (same-host processes): bounds the migration pause.
        "exported_wall": time.time(),
    }
    hdr = json.dumps(header).encode()
    return b"".join(
        [MIGRATE_MAGIC, struct.pack("<I", len(hdr)), hdr, wire]
    )


def peek_header(payload: bytes) -> Dict[str, object]:
    """The scheduler-state header alone — routers size destinations
    (``n_blocks``) without touching the KV bytes."""
    if payload[:4] != MIGRATE_MAGIC:
        raise MigrationError("bad migration payload magic")
    (hlen,) = struct.unpack_from("<I", payload, 4)
    return json.loads(payload[8:8 + hlen].decode())


def release_exported(engine, req: Request,
                     now: Optional[float] = None) -> None:
    """Source-side release after the importer acked: recycle the slot,
    decref the blocks (prefix-cached prompt blocks keep the cache's
    ref — conservation holds), and record a ``migrated`` outcome. The
    request's spans are emitted by the DESTINATION: the source emits
    nothing, or the request would double-report."""
    if req.state == DECODE and req.slot >= 0:
        slot = req.slot
        engine.scheduler.evict(req, now)
        engine._release_slot(req, slot)
        engine._lengths[slot] = 0
        engine._tokens[slot] = 0
        engine._temps[slot] = 0.0
    engine.metrics.requests.inc(outcome="migrated")
    engine.metrics.annotate("serving_migrate_out", rid=req.rid)


def can_import(engine, n_blocks: int) -> bool:
    """Cheap admission probe: a free slot plus ``n_blocks`` coverable
    by free + evictable-cache blocks (the import never preempts a
    peer — a migration must not burn another request's prefill)."""
    if engine.scheduler.free_slots() < 1:
        return False
    stats = engine._allocator.stats(engine._live_block_ids())
    return stats["free"] + stats["cached"] >= n_blocks


def import_request(engine, payload: bytes,
                   trace: Optional[dict] = None) -> Request:
    """Admit a migrated request into ``engine`` mid-stream (see module
    docstring). Raises :class:`MigrationRefused` when the engine
    cannot hold it, :class:`MigrationError` on incompatibility."""
    t_in = time.monotonic()
    header = peek_header(payload)
    (hlen,) = struct.unpack_from("<I", payload, 4)
    kq, vq, ks, vs, _ = kv_from_wire(payload[8 + hlen:])
    L, n, bs, kh, hd = kq.shape
    cfg = engine.config
    if (L, kh, hd) != (cfg.n_layers, cfg.n_kv_heads, cfg.head_dim):
        raise MigrationError(
            f"model shape mismatch: wire {(L, kh, hd)} vs engine "
            f"{(cfg.n_layers, cfg.n_kv_heads, cfg.head_dim)}"
        )
    if bs != engine.block_size or bs != header["block_size"]:
        raise MigrationError(
            f"block_size mismatch: wire {bs} vs engine "
            f"{engine.block_size}"
        )
    if n != header["n_blocks"] or n > engine.max_blocks:
        raise MigrationError(
            f"{n} wire blocks vs header {header['n_blocks']} / "
            f"table capacity {engine.max_blocks}"
        )
    fill = int(header["fill"])
    if fill > n * bs:
        raise MigrationError(f"fill {fill} exceeds {n} wire blocks")
    if not can_import(engine, n):
        raise MigrationRefused(
            f"destination full: {n} blocks + a slot needed"
        )
    slo = header["slo_class"]
    if slo not in engine.scheduler.slo_classes:
        # A stock single-class destination must not reject tagged
        # traffic mid-migration; untagged is the local default.
        slo = None
    req = engine.scheduler.admit_decode(
        np.asarray(header["prompt"], np.int32),
        [int(t) for t in header["tokens"]],
        int(header["max_new_tokens"]),
        temperature=float(header["temperature"]),
        slo_class=slo,
        now=t_in,
    )
    slot = req.slot
    try:
        blocks = engine._alloc_blocks(n, req)
    except Exception:
        engine.scheduler.evict(req)
        raise
    engine._tables[slot, :] = 0  # SENTINEL_BLOCK
    engine._slot_blocks[slot] = list(blocks)
    engine._tables[slot, :n] = blocks
    engine._lengths[slot] = fill
    engine._tokens[slot] = req.tokens[-1]
    engine._temps[slot] = req.temperature
    if engine._quantized:
        for i, dst in enumerate(blocks):
            engine._set_pools(engine._steps.imp(
                *engine._pools(),
                jnp.asarray(kq[:, i]), jnp.asarray(vq[:, i]),
                jnp.asarray(ks[:, i]), jnp.asarray(vs[:, i]),
                np.int32(dst),
            ))
    else:
        # fp destination: dequantize the int8 wire rows on the host
        # (q * scale is exact in f32 — the idempotent-roundtrip rule).
        kf = kq.astype(np.float32) * ks[..., None]
        vf = vq.astype(np.float32) * vs[..., None]
        for i, dst in enumerate(blocks):
            engine._set_pools(engine._steps.imp(
                *engine._pools(),
                jnp.asarray(kf[:, i]), jnp.asarray(vf[:, i]),
                np.int32(dst),
            ))
    if engine._cache is not None:
        # Imported chains join the destination trie: the NEXT request
        # sharing this prompt hits warm blocks — hit-rate survives
        # migration. Partial tails stay private (decode appends there).
        n_full = req.prompt_len // bs
        if n_full:
            engine._cache.insert(req.prompt, blocks[:n_full])
    # Timeline on the LOCAL monotonic clock: the migrate window ends
    # now; its start is bounded by the wall-clock export stamp; the
    # source phases hang off it by their carried durations.
    t_done = time.monotonic()
    pause = max(time.time() - header["exported_wall"], t_done - t_in)
    req.migrate_end_ts = t_done
    req.migrate_start_ts = t_done - pause
    req.first_token_ts = req.migrate_start_ts - header["decode_s"]
    req.admit_ts = req.first_token_ts - header["prefill_s"]
    req.submit_ts = req.admit_ts - header["queue_s"]
    remaining = header.get("deadline_remaining_s")
    req.deadline = (
        t_done + remaining if remaining is not None else None
    )
    req.prefix_hit_blocks = int(header.get("prefix_hit_blocks", 0))
    req.trace = trace
    engine.metrics.requests.inc(outcome="imported")
    engine.metrics.annotate(
        "serving_import", rid=req.rid, src_rid=header["src_rid"],
        blocks=n, fill=fill, pause_s=round(pause, 6),
    )
    logger.debug(
        "imported rid %d (src rid %d): %d blocks, fill %d, pause %.1f"
        "ms", req.rid, header["src_rid"], n, fill, pause * 1e3,
    )
    return req
