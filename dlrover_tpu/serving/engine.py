"""Continuous-batching decode engine: slot-pooled KV cache, ragged
per-slot fills, iteration-level scheduling.

The only inference entry point before this was ``models/generate.py`` —
a fixed-batch ``lax.scan`` whose fill cursor is shared by every row, so
a batch can only hold same-phase sequences and admitting new work means
draining the batch and re-prefilling everything. This engine is the
serving-shaped alternative (Orca's iteration-level scheduling over this
repo's single-slab cache — the TPU-native analogue of vLLM's pooled
blocks, one slot = one sequence's [max_len] slab):

- **Slot pool.** One [layers, slots, max_len, kv_heads, head_dim] K and
  V slab, allocated once, DONATED through every step call so XLA
  updates it in place — admissions/evictions/completions never change a
  traced shape; occupancy is a [slots] mask and per-slot fill lengths
  are a [slots] int32 vector.
- **Ragged decode.** One compiled step decodes every active slot at its
  OWN fill length: per-row positions drive RoPE, per-row masking drives
  the append-free attention (models/generate._append_free_attention),
  and the append is a per-row scatter at each slot's cursor. Inactive
  slots compute masked garbage that lands only in never-visible rows
  (the visibility invariant, docs/DESIGN.md §25).
- **Chunked prefill.** Prompts enter ``prefill_chunk`` tokens at a time
  through a second compiled program (one slot per call), so a long
  prompt interleaves with decode iterations instead of stalling them.
- **Zero retraces.** Both programs are compiled once per
  (config, slots, max_len, chunk) and every dynamic quantity — slot
  index, cursor, lengths, occupancy, temperatures, sampling step — is a
  traced argument. ``trace_counts`` exposes the compile counter the
  no-retrace tests and the serving bench assert on.

Typical use::

    eng = ServingEngine(cfg, params, slots=8, max_len=1024)
    eng.submit(prompt_ids, max_new_tokens=64, temperature=0.8)
    while eng.pending():
        for req in eng.step():
            consume(req.rid, req.tokens)
"""

import functools
import time
from typing import Dict, List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point
from dlrover_tpu.models import generate as gen_lib
from dlrover_tpu.observability import tracing
from dlrover_tpu.models import llama
from dlrover_tpu.serving import scheduler as sched_lib
from dlrover_tpu.serving import spec_decode as spec_lib
from dlrover_tpu.serving.metrics import serving_metrics
from dlrover_tpu.serving.scheduler import DECODE, PREFILL, Request, Scheduler


class _CompiledSteps(NamedTuple):
    prefill: object
    decode: object
    trace_counts: Dict[str, int]


# Lookback horizon for the host n-gram drafter: the rightmost suffix
# match decides the proposal, so only recent history can win — and the
# per-step host cost must stay flat as sequences grow.
_NGRAM_WINDOW = 128


class _SpecSteps(NamedTuple):
    """Speculative-decoding programs, compiled SEPARATELY from the
    base prefill/decode pair: a spec-on and a spec-off engine with the
    same (config, slots, max_len, chunk) share one _CompiledSteps entry
    — the bench's spec A/B genuinely runs on the same compiled base
    programs, and spec_k changes can't invalidate them."""

    verify: object
    draft: object        # None for the host-side n-gram drafter
    trace_counts: Dict[str, int]


def _build_decode_step(config, slots: int, max_len: int, counts):
    """[slots] tokens -> one decoded token per slot, ragged lengths.

    The cache is read by the layer scan (append-free attention) and the
    new K/V of ALL layers land with one per-row scatter at each slot's
    own cursor — the ragged generalization of generate()'s single
    dynamic-update-slice."""

    def step(k, v, params, lengths, tokens, active, temps, rng, step_idx):
        counts["decode"] += 1  # traces only; execution never reaches here
        positions = lengths[:, None]                     # [slots, 1]
        x = llama.embed_tokens(config, params, tokens[:, None])

        def body(carry, layer_in):
            pl, k_c, v_c = layer_in
            y, k_new, v_new = gen_lib._layer_decode_read_only(
                config, pl, carry, positions, k_c, v_c, lengths
            )
            return y, (k_new, v_new)

        x, (k_news, v_news) = jax.lax.scan(
            body, x, (params["layers"], k, v)
        )
        # Per-row append at each slot's cursor. Inactive slots write
        # garbage into rows that are not visible (>= fill) and are
        # always rewritten before any cursor passes them; the clamp
        # keeps a full stale slot's scatter in bounds.
        row = jnp.arange(slots)
        write = jnp.minimum(lengths, max_len - 1)
        k = k.at[:, row, write].set(k_news[:, :, 0].astype(k.dtype))
        v = v.at[:, row, write].set(v_news[:, :, 0].astype(v.dtype))
        logits = llama.unembed(config, params, x)[:, 0]   # [slots, V]
        sub = jax.random.fold_in(rng, step_idx * 2)
        nxt = gen_lib.sample_token(logits, sub, temps)
        # Inactive slots keep their fed token (the host ignores them,
        # but a stable value keeps replays deterministic).
        nxt = jnp.where(active, nxt, tokens)
        return k, v, nxt

    return step


def _build_prefill_chunk(config, slots: int, max_len: int, chunk: int,
                         counts):
    """One prompt chunk ([1, chunk] tokens) into ONE slot's cache rows
    [start, start+chunk), plus the first sampled token (meaningful only
    on the final chunk — taken at the last REAL prompt position
    ``n_valid - 1``; pad rows beyond it hold garbage K/V that stays
    invisible)."""

    L = config.n_layers
    kh, hd = config.n_kv_heads, config.head_dim

    def prefill(k, v, params, tokens, slot, start, n_valid, temp, rng,
                step_idx):
        counts["prefill"] += 1  # traces only
        k_slot = jax.lax.dynamic_slice(
            k, (0, slot, 0, 0, 0), (L, 1, max_len, kh, hd)
        )
        v_slot = jax.lax.dynamic_slice(
            v, (0, slot, 0, 0, 0), (L, 1, max_len, kh, hd)
        )
        positions = (
            start + jnp.arange(chunk, dtype=jnp.int32)
        )[None, :]
        x = llama.embed_tokens(config, params, tokens)

        def body(carry, layer_in):
            pl, k_c, v_c = layer_in
            y, k_c, v_c = gen_lib._layer_decode(
                config, pl, carry, positions, k_c, v_c, start,
                attn_impl="xla",
            )
            return y, (k_c, v_c)

        x, (k_slot, v_slot) = jax.lax.scan(
            body, x, (params["layers"], k_slot, v_slot)
        )
        k = jax.lax.dynamic_update_slice(
            k, k_slot.astype(k.dtype), (0, slot, 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            v, v_slot.astype(v.dtype), (0, slot, 0, 0, 0)
        )
        h = jax.lax.dynamic_slice_in_dim(x, n_valid - 1, 1, axis=1)
        logits = llama.unembed(config, params, h)[0, 0]    # [V]
        sub = jax.random.fold_in(rng, step_idx * 2 + 1)
        first = gen_lib.sample_token(logits, sub, temp)
        return k, v, first

    return prefill


def _build_verify_step(config, slots: int, max_len: int, K: int, counts):
    """[slots] fed tokens + [slots, K] drafts -> accepted tokens, one
    batched pass. T = K+1 queries run the SAME ragged append-free
    attention as the decode step (generalized to multiple queries with
    an intra-draft causal mask — models/generate._layer_verify_read_
    only), all T rows' K/V land with one per-row scatter at rows
    fill..fill+K, and the accept/reject law (spec_decode.spec_accept)
    picks how many drafts survive. Rows past an accepted prefix stay
    beyond the advanced fill — rollback is the fill rewind, no cleanup
    pass exists. Writes past max_len drop (``mode="drop"``): near the
    boundary the host clamps draft_len so no DROPPED row can ever
    become visible."""
    T = K + 1

    def verify(k, v, params, lengths, tokens, drafts, draft_len,
               active, temps, rng, step_idx):
        counts["verify"] += 1  # traces only
        toks = jnp.concatenate([tokens[:, None], drafts], axis=1)
        positions = (
            lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        )
        x = llama.embed_tokens(config, params, toks)

        def body(carry, layer_in):
            pl, k_c, v_c = layer_in
            y, k_new, v_new = gen_lib._layer_verify_read_only(
                config, pl, carry, positions, k_c, v_c, lengths
            )
            return y, (k_new, v_new)

        x, (k_news, v_news) = jax.lax.scan(
            body, x, (params["layers"], k, v)
        )
        row = jnp.arange(slots)[:, None]
        writes = positions                                # [slots, T]
        k = k.at[:, row, writes].set(
            k_news.astype(k.dtype), mode="drop"
        )
        v = v.at[:, row, writes].set(
            v_news.astype(v.dtype), mode="drop"
        )
        logits = llama.unembed(config, params, x)         # [slots, T, V]
        emitted, acc = spec_lib.spec_accept(
            logits, drafts, draft_len, temps, active, tokens,
            rng, step_idx,
        )
        return k, v, emitted, acc

    return verify


def _build_draft_step(config, slots: int, max_len: int, K: int,
                      draft_layers: int, counts):
    """Early-exit drafter: K sequential single-token forwards through
    the FIRST ``draft_layers`` decoder blocks of the same weights
    (greedy argmax through the shared final-norm/unembed head). Each
    drafted token's partial-layer K/V lands at its row beyond the fill
    so the NEXT draft can attend it — invisible to everyone else by
    the visibility invariant, and the verify pass rewrites those rows
    with full-model K/V for every layer before any of them can become
    visible. Out-of-range writes drop."""
    d = draft_layers

    def draft(k, v, params, lengths, tokens, active):
        counts["draft"] += 1  # traces only
        layers_d = jax.tree_util.tree_map(
            lambda a: a[:d], params["layers"]
        )
        row = jnp.arange(slots)
        cur = tokens
        drafts = []
        for i in range(K):
            lens_i = lengths + i
            positions = lens_i[:, None]
            x = llama.embed_tokens(config, params, cur[:, None])

            def body(carry, layer_in):
                pl, k_c, v_c = layer_in
                y, k_new, v_new = gen_lib._layer_decode_read_only(
                    config, pl, carry, positions, k_c, v_c, lens_i
                )
                return y, (k_new, v_new)

            x, (k_news, v_news) = jax.lax.scan(
                body, x, (layers_d, k[:d], v[:d])
            )
            k = k.at[:d, row, lens_i].set(
                k_news[:, :, 0].astype(k.dtype), mode="drop"
            )
            v = v.at[:d, row, lens_i].set(
                v_news[:, :, 0].astype(v.dtype), mode="drop"
            )
            logits = llama.unembed(config, params, x)[:, 0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            cur = jnp.where(active, nxt, cur)
            drafts.append(cur)
        return k, v, jnp.stack(drafts, axis=1)           # [slots, K]

    return draft


@functools.lru_cache(maxsize=16)
def _compiled_spec_steps(
    config: llama.TpuLMConfig, slots: int, max_len: int,
    spec_k: int, draft_layers: int,
) -> _SpecSteps:
    """Verify (+ optional early-exit draft) programs, one per shape
    key, KV slabs donated — the spec siblings of _compiled_steps.
    spec_k is a SHAPE key (the verify batch is [slots, K+1]); the
    per-slot accept length rides as a traced vector, so variable
    acceptance never retraces."""
    counts = {"verify": 0, "draft": 0}
    verify = jax.jit(
        _build_verify_step(config, slots, max_len, spec_k, counts),
        donate_argnums=(0, 1),
    )
    draft = None
    if draft_layers > 0:
        draft = jax.jit(
            _build_draft_step(config, slots, max_len, spec_k,
                              draft_layers, counts),
            donate_argnums=(0, 1),
        )
    return _SpecSteps(verify=verify, draft=draft, trace_counts=counts)


@functools.lru_cache(maxsize=16)
def _compiled_steps(
    config: llama.TpuLMConfig, slots: int, max_len: int, chunk: int
) -> _CompiledSteps:
    """Both step programs, compiled once per shape key and SHARED by
    every engine with the same key (the bench's continuous and static
    engines reuse one compile). The KV slabs are donated so the pool is
    updated in place; everything else is a plain traced argument."""
    counts = {"prefill": 0, "decode": 0}
    decode = jax.jit(
        _build_decode_step(config, slots, max_len, counts),
        donate_argnums=(0, 1),
    )
    prefill = jax.jit(
        _build_prefill_chunk(config, slots, max_len, chunk, counts),
        donate_argnums=(0, 1),
    )
    return _CompiledSteps(prefill=prefill, decode=decode,
                          trace_counts=counts)


class ServingEngine:
    """Single-host continuous-batching engine over a slot-pooled cache.

    Host bookkeeping (the Scheduler) is jax-free; each ``step()`` runs
    at most one prefill chunk and one ragged decode iteration. The
    engine is not thread-safe — drive it from one serving loop."""

    def __init__(
        self,
        config: llama.TpuLMConfig,
        params,
        slots: int,
        max_len: int,
        prefill_chunk: int = 64,
        token_budget: Optional[int] = None,
        drain_mode: bool = False,
        rng: Optional[jax.Array] = None,
        registry=None,
        max_requeues: int = 3,
        slo_classes=None,
        spec_k: int = 0,
        spec_drafter: str = "ngram",
        spec_draft_layers: int = 2,
    ):
        if config.pp_stages > 1:
            raise NotImplementedError(
                "serving runs on the flat layer stack; merge pipeline "
                "stages for inference"
            )
        if max_len % 8:
            raise ValueError("max_len must be a multiple of 8")
        if max_len % prefill_chunk:
            # The final chunk of a near-full prompt would otherwise
            # start at a non-chunk-aligned cursor close enough to the
            # end that its fixed-size dynamic_update_slice CLAMPS —
            # silently rewriting already-visible rows below the cursor
            # with K/V computed for later positions. Chunk starts are
            # always multiples of prefill_chunk (partial chunks only
            # ever END a prompt), so divisibility makes the clamp
            # unreachable.
            raise ValueError(
                f"max_len {max_len} must be a multiple of "
                f"prefill_chunk {prefill_chunk}"
            )
        if spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if spec_k:
            if spec_drafter not in spec_lib.SPEC_DRAFTERS:
                raise ValueError(
                    f"spec_drafter must be one of "
                    f"{spec_lib.SPEC_DRAFTERS}, got {spec_drafter!r}"
                )
            if spec_drafter == "early_exit" and not (
                0 < spec_draft_layers <= config.n_layers
            ):
                raise ValueError(
                    f"spec_draft_layers must be in 1..{config.n_layers}"
                )
        self.config = config
        self.slots = slots
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        self.spec_k = spec_k
        self.spec_drafter = spec_drafter
        # draft_layers keys the compile cache; 0 = no device drafter
        # (the n-gram drafter is pure host code).
        self.spec_draft_layers = (
            spec_draft_layers
            if spec_k and spec_drafter == "early_exit" else 0
        )
        # How many step-error restarts a request gets before it is
        # EXPLICITLY failed — a persistent device error must not
        # livelock the serve loop re-queueing the same work forever.
        self.max_requeues = max_requeues
        self.scheduler = Scheduler(
            slots, max_len, prefill_chunk, token_budget, drain_mode,
            slo_classes=slo_classes,
            decode_tokens_per_slot=1 + spec_k,
        )
        self.metrics = serving_metrics(registry)
        self.metrics.slots_total.set(slots)
        self._params = gen_lib.prepare_decode_params(config, params)
        self._steps = _compiled_steps(config, slots, max_len,
                                      prefill_chunk)
        self._spec = (
            _compiled_spec_steps(config, slots, max_len, spec_k,
                                 self.spec_draft_layers)
            if spec_k else None
        )
        # Running accepted-tokens-per-step mean (slot-steps in the
        # denominator: one decoding slot through one verify call).
        self._spec_emitted = 0
        self._spec_slot_steps = 0
        # Per-iteration emitted-token counts, one entry per decoding
        # slot — step() turns them into per-TOKEN latency observations
        # (a verify step that commits 4 tokens is 4 cheap tokens, not
        # one slow one).
        self._iter_advance: List[int] = []
        self._trace_snapshot = self._all_trace_counts()
        self._rng = rng if rng is not None else jax.random.key(0)
        self._step_idx = 0
        self._k, self._v = self._fresh_pool()
        # Host mirrors of the device-side per-slot state; passed into
        # every step call (tiny H2D) so host and device can never
        # drift.
        self._lengths = np.zeros(slots, np.int32)
        self._tokens = np.zeros(slots, np.int32)
        self._temps = np.zeros(slots, np.float32)

    def _fresh_pool(self):
        shape = (
            self.config.n_layers, self.slots, self.max_len,
            self.config.n_kv_heads, self.config.head_dim,
        )
        dtype = self.config.compute_dtype
        return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)

    # ---- public API --------------------------------------------------------

    def _all_trace_counts(self) -> Dict[str, int]:
        """Base + spec compile counters merged (key sets are disjoint:
        prefill/decode/... vs verify/draft)."""
        counts = dict(self._steps.trace_counts)
        if self._spec is not None:
            counts.update(self._spec.trace_counts)
        return counts

    @property
    def trace_counts(self) -> Dict[str, int]:
        """Compile counter per step program (shared across engines with
        the same shape key) — flat after warmup or something retraced."""
        return self._all_trace_counts()

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_s: Optional[float] = None,
               trace: Optional[dict] = None,
               slo_class: Optional[str] = None) -> Request:
        req = self.scheduler.submit(
            prompt, max_new_tokens, temperature, deadline_s=deadline_s,
            slo_class=slo_class,
        )
        # Upstream trace carrier (fleet attempt span): stored as a
        # plain dict; the phase spans are emitted retrospectively at
        # completion, so the step loop never touches the tracer.
        req.trace = trace
        self.metrics.queue_depth.set(len(self.scheduler.queue))
        return req

    def cancel(self, req: Request) -> None:
        """Evict a live request; its slot is recycled immediately."""
        if req.state == sched_lib.DONE:
            return
        if req.state == sched_lib.QUEUED:
            try:
                self.scheduler.queue.remove(req)
            except ValueError:
                pass
        slot = req.slot
        self.scheduler.evict(req)
        if slot >= 0:
            self._release_slot(req, slot)
        self.metrics.requests.inc(outcome="cancelled")
        self.metrics.annotate("serving_evict", rid=req.rid)

    def pending(self) -> int:
        """Requests not yet DONE (queued + in a slot)."""
        return len(self.scheduler.queue) + len(self.scheduler.active())

    def warmup(self) -> None:
        """Compile both step programs on throwaway state, then reset the
        pool — so the first real request pays no compile and the
        trace counters are settled for no-retrace assertions."""
        chunk = np.zeros((1, self.prefill_chunk), np.int32)
        k, v, first = self._steps.prefill(
            self._k, self._v, self._params, jnp.asarray(chunk),
            np.int32(0), np.int32(0), np.int32(1), np.float32(0.0),
            self._rng, np.int32(0),
        )
        k, v, nxt = self._steps.decode(
            k, v, self._params,
            jnp.asarray(np.zeros(self.slots, np.int32)),
            jnp.asarray(np.zeros(self.slots, np.int32)),
            jnp.asarray(np.zeros(self.slots, bool)),
            jnp.asarray(np.zeros(self.slots, np.float32)),
            self._rng, np.int32(0),
        )
        if self._spec is not None:
            z_i = jnp.asarray(np.zeros(self.slots, np.int32))
            z_b = jnp.asarray(np.zeros(self.slots, bool))
            z_f = jnp.asarray(np.zeros(self.slots, np.float32))
            drafts = jnp.asarray(
                np.zeros((self.slots, self.spec_k), np.int32)
            )
            if self._spec.draft is not None:
                k, v, drafts = self._spec.draft(
                    k, v, self._params, z_i, z_i, z_b
                )
            k, v, _, acc = self._spec.verify(
                k, v, self._params, z_i, z_i, drafts, z_i, z_b, z_f,
                self._rng, np.int32(0),
            )
            nxt = acc
        jax.block_until_ready(nxt)
        del k, v
        self._k, self._v = self._fresh_pool()
        self._trace_snapshot = self._all_trace_counts()

    def step(self) -> List[Request]:
        """One scheduler iteration: admissions, at most one prefill
        chunk, one ragged decode step. Returns requests finished THIS
        iteration (tokens fully populated)."""
        t0 = time.monotonic()
        sch = self.scheduler
        finished: List[Request] = []
        self._iter_advance = []
        for req in sch.shed_expired(t0):
            # Past-deadline queued work is an explicit terminal outcome,
            # surfaced through step()'s return like any completion.
            self._report_shed(req, finished)
        for req in sch.admit(t0):
            self._admit_slot(req)
            if req.requeues == 0:
                # Re-admission after a step-error requeue is not a new
                # request: counting it again would skew done/admitted
                # completion-rate dashboards.
                self.metrics.requests.inc(outcome="admitted")
            self.metrics.annotate(
                "serving_admit", rid=req.rid, slot=req.slot,
                prompt_len=req.prompt_len, requeues=req.requeues,
            )
        for req in sch.drain_admission_shed():
            # Deadline lapsed while waiting for a free slot: shed at
            # the admission decision, same terminal surface.
            self._report_shed(req, finished)
        try:
            fault_point("serving.step.error", step_idx=self._step_idx)
            pf = sch.pick_prefill()
            if pf is not None:
                self._run_prefill_chunk(pf, finished)
            decoding = sch.decoding()
            if decoding:
                self._run_decode(decoding, finished)
        except Exception as e:  # noqa: BLE001 — device/XLA errors vary
            self._recover_from_step_error(e, finished)
            self._iter_advance = []
        self._step_idx += 1
        self.metrics.iterations.inc()
        self.metrics.queue_depth.set(len(sch.queue))
        for name, depth in sch.queue_depth_by_class().items():
            self.metrics.class_queue_depth.set(depth, slo_class=name)
        self.metrics.active_slots.set(len(sch.active()))
        self._sync_pool_metrics()
        self._sync_retrace_metric()
        if self._iter_advance:
            # One observation PER EMITTED TOKEN at the per-token cost,
            # not one per iteration at the full wall time — a verify
            # step committing 4 tokens per slot must read as 4 fast
            # tokens, or spec decode would look SLOWER per token the
            # better it performs.
            dt = time.monotonic() - t0
            per_tok = dt / sum(self._iter_advance)
            for adv in self._iter_advance:
                for _ in range(adv):
                    self.metrics.token_latency.observe(per_tok)
        return finished

    def run_until_idle(self, max_iters: int = 100000) -> List[Request]:
        """Drive step() until nothing is pending; returns all finished."""
        done: List[Request] = []
        for _ in range(max_iters):
            if not self.pending():
                return done
            done.extend(self.step())
        raise RuntimeError(
            f"engine did not drain within {max_iters} iterations"
        )

    # ---- pool hooks (overridden by the paged engine, serving/kvpool) -------

    def _admit_slot(self, req: Request) -> None:
        """Bind engine-side per-slot state for a freshly admitted
        request. A recycled slot starts from fill 0: stale KV above the
        cursor is invisible and rewritten before visibility."""
        self._lengths[req.slot] = 0
        self._tokens[req.slot] = 0
        self._temps[req.slot] = req.temperature

    def _release_slot(self, req: Request, slot: int) -> None:
        """A request left its slot (finish/cancel). The flat pool has
        nothing to reclaim — stale rows are invisible; the paged engine
        returns the slot's blocks to the allocator here."""

    def _reset_pool(self) -> None:
        """Rebuild ALL device-side cache state after a failed step call
        (donated buffers may be invalidated)."""
        self._k, self._v = self._fresh_pool()

    def _sync_pool_metrics(self) -> None:
        """Per-iteration pool gauges; the flat pool has none beyond the
        slot gauges step() already sets."""

    def _report_shed(self, req: Request, finished: List[Request]) -> None:
        finished.append(req)
        self.metrics.shed.inc(reason="deadline", slo_class=req.slo_class)
        self.metrics.requests.inc(outcome="shed")
        self.metrics.failures.inc(reason="deadline")
        self.metrics.annotate(
            "serving_shed", rid=req.rid, reason="deadline",
            slo_class=req.slo_class,
        )
        self._emit_request_spans(req, status="error")

    # ---- internals ---------------------------------------------------------

    def _recover_from_step_error(self, err: BaseException,
                                 finished: List[Request]):
        """A compiled step raised (device fault, XLA error, injected
        chaos). The donated KV slabs may have been invalidated by the
        failed call, so NOTHING cached on device survives: rebuild the
        pool and return every in-flight request to the front of the
        queue to restart from scratch. A request that keeps landing in
        a raising step is EXPLICITLY failed after ``max_requeues``
        restarts — admitted work is never silently lost, and a
        persistent error cannot livelock the serve loop. Failed
        requests surface through ``finished`` with ``failed=True``."""
        # Progress about to be reset IS the wasted work: prompt rows
        # already prefilled and tokens already decoded replay from
        # scratch (§34 useful-token accounting).
        active = self.scheduler.active()
        wasted_prefill = sum(r.prefill_pos for r in active)
        wasted_decode = sum(len(r.tokens) for r in active)
        requeued = self.scheduler.requeue_active()
        self._reset_pool()
        self._lengths[:] = 0
        self._tokens[:] = 0
        self._temps[:] = 0.0
        self.metrics.step_errors.inc()
        if wasted_prefill:
            self.metrics.tokens_wasted.inc(wasted_prefill, kind="prefill")
        if wasted_decode:
            self.metrics.tokens_wasted.inc(wasted_decode, kind="decode")
        failed = 0
        for req in requeued:
            if req.requeues > self.max_requeues:
                try:
                    self.scheduler.queue.remove(req)
                except ValueError:
                    pass
                req.failed = True
                req.failure_reason = "requeue_budget"
                self.scheduler.finish(req)
                finished.append(req)
                self._emit_request_spans(req, status="error")
                failed += 1
                self.metrics.requests.inc(outcome="failed")
                self.metrics.failures.inc(reason="requeue_budget")
            else:
                self.metrics.requests.inc(outcome="requeued")
        self.metrics.annotate(
            "serving_step_error",
            error=f"{type(err).__name__}: {err}"[:200],
            requeued=len(requeued) - failed, failed=failed,
        )
        logger.warning(
            "serving step raised (%s: %s); pool rebuilt, %d in-flight "
            "request(s) re-queued, %d explicitly failed",
            type(err).__name__, err, len(requeued) - failed, failed,
        )

    def _run_prefill_chunk(self, req: Request, finished: List[Request]):
        c = self.prefill_chunk
        start = req.prefill_pos
        n_valid = min(c, req.prompt_len - start)
        chunk = np.zeros((1, c), np.int32)
        chunk[0, :n_valid] = req.prompt[start:start + n_valid]
        self._k, self._v, first = self._steps.prefill(
            self._k, self._v, self._params, jnp.asarray(chunk),
            np.int32(req.slot), np.int32(start), np.int32(n_valid),
            np.float32(req.temperature), self._rng,
            np.int32(self._step_idx),
        )
        req.prefill_pos += n_valid
        self._lengths[req.slot] = req.prefill_pos
        self.metrics.tokens.inc(n_valid, kind="prefill")
        if req.prefill_pos < req.prompt_len:
            return  # more chunks to come; `first` is discarded unfetched
        tok = int(jax.device_get(first))
        req.first_token_ts = time.monotonic()
        if req.requeues == 0:
            # A re-run after a step-error requeue would re-observe an
            # inflated first-token latency for the same request.
            self.metrics.ttft.observe(req.ttft_s)
        req.tokens.append(tok)
        self._tokens[req.slot] = tok
        self.metrics.tokens.inc(kind="decode")
        if len(req.tokens) >= req.max_new_tokens:
            self._finish(req, finished)
        else:
            req.state = DECODE

    def _run_decode(self, decoding: List[Request],
                    finished: List[Request]):
        if self.spec_k:
            self._run_decode_spec(decoding, finished)
            return
        active = np.zeros(self.slots, bool)
        for r in decoding:
            active[r.slot] = True
        self._k, self._v, nxt = self._steps.decode(
            self._k, self._v, self._params,
            jnp.asarray(self._lengths), jnp.asarray(self._tokens),
            jnp.asarray(active), jnp.asarray(self._temps),
            self._rng, np.int32(self._step_idx),
        )
        nxt = np.asarray(jax.device_get(nxt))
        for r in decoding:
            self._lengths[r.slot] += 1   # the fed token's KV landed
            tok = int(nxt[r.slot])
            r.tokens.append(tok)
            self._tokens[r.slot] = tok
            self.metrics.tokens.inc(kind="decode")
            self._iter_advance.append(1)
            if len(r.tokens) >= r.max_new_tokens:
                self._finish(r, finished)
            elif self._lengths[r.slot] + 1 > self.max_len:
                # No room to feed the token just sampled.
                r.truncated = True
                self._finish(r, finished)

    # ---- speculative decode (§35) ------------------------------------------

    def _run_decode_spec(self, decoding: List[Request],
                         finished: List[Request]):
        """One draft → verify → commit iteration for every decoding
        slot. The verify program replaces the decode program entirely
        while spec is on (draft_len 0 degenerates to plain one-token
        decode), so variable per-slot acceptance is just a ragged fill
        advance — the SAME continuous-batching law as everything else,
        zero retraces. Rollback of rejected drafts is the fill NOT
        advancing past them."""
        decoding = self._spec_prepare_rows(decoding)
        if not decoding:
            return
        active = np.zeros(self.slots, bool)
        for r in decoding:
            active[r.slot] = True
        t_d = time.monotonic()
        drafts, draft_len = self._spec_draft(decoding, active)
        t_v = time.monotonic()
        emitted, acc = self._spec_verify_device(active, drafts,
                                                draft_len)
        emitted = np.asarray(jax.device_get(emitted))
        acc = np.asarray(jax.device_get(acc))
        t_e = time.monotonic()
        n_dec = len(decoding)
        d_dt = (t_v - t_d) / n_dec
        v_dt = (t_e - t_v) / n_dec
        for r in decoding:
            r.draft_s += d_dt
            r.verify_s += v_dt
        for r in decoding:
            s = r.slot
            n_acc = int(acc[s])
            dl = int(draft_len[s])
            toks = [int(t) for t in emitted[s, : n_acc + 1]]
            # All T rows' KV landed; only the accepted prefix plus the
            # final token become visible — the rest sits beyond the
            # fill (free rollback).
            self._lengths[s] += n_acc + 1
            r.tokens.extend(toks)
            self._tokens[s] = toks[-1]
            r.spec_drafted += dl
            r.spec_accepted += n_acc
            self.metrics.tokens.inc(n_acc + 1, kind="decode")
            if dl:
                self.metrics.spec_tokens.inc(dl, kind="drafted")
                if n_acc:
                    self.metrics.spec_tokens.inc(n_acc, kind="accepted")
                if dl - n_acc:
                    self.metrics.spec_tokens.inc(dl - n_acc,
                                                 kind="rejected")
                self.metrics.spec_accept_rate.observe(n_acc / dl)
            self._spec_emitted += n_acc + 1
            self._spec_slot_steps += 1
            self._iter_advance.append(n_acc + 1)
            if len(r.tokens) >= r.max_new_tokens:
                self._finish(r, finished)
            elif self._lengths[s] + 1 > self.max_len:
                # No room to feed the final token back.
                r.truncated = True
                self._finish(r, finished)
        self.metrics.spec_tokens_per_step.set(
            self._spec_emitted / self._spec_slot_steps
        )

    def _spec_prepare_rows(self, decoding: List[Request]):
        """Make rows fill..fill+spec_k writable for every decoding
        slot. The flat slab always has them (each slot owns [max_len]
        rows); the paged engine allocates/privatizes blocks here and
        may preempt."""
        return decoding

    def _spec_draft(self, decoding: List[Request], active):
        """Propose up to spec_k tokens per slot. Returns
        ``(drafts [slots, K], draft_len np[slots])`` — drafts may live
        on device (early exit) or host (n-gram)."""
        K = self.spec_k
        draft_len = np.zeros(self.slots, np.int32)
        caps = {
            r.slot: spec_lib.clamp_draft_len(
                K, len(r.tokens), r.max_new_tokens,
                int(self._lengths[r.slot]), self.max_len,
            )
            for r in decoding
        }
        if self.spec_drafter == "early_exit":
            drafts = self._spec_draft_device(active)
            # The device drafter always proposes K tokens; the clamp
            # rides in draft_len (acceptance never crosses it).
            jax.block_until_ready(drafts)  # honest draft/verify split
            for s, cap in caps.items():
                draft_len[s] = cap
            return drafts, draft_len
        drafts_np = np.zeros((self.slots, K), np.int32)
        window = _NGRAM_WINDOW
        for r in decoding:
            s = r.slot
            cap = caps[s]
            if cap <= 0:
                continue
            # Bounded lookback: the rightmost match is what wins, and
            # the motifs worth speculating on recur within a short
            # horizon — an unbounded concat would make the host draft
            # cost grow with sequence length every step.
            toks = r.tokens
            if len(toks) >= window:
                hist = np.asarray(toks[-window:], np.int32)
            else:
                hist = np.concatenate([
                    np.asarray(
                        r.prompt[-(window - len(toks)):], np.int32
                    ),
                    np.asarray(toks, np.int32),
                ])
            prop = spec_lib.propose_ngram(hist, cap)
            n = min(len(prop), cap)
            drafts_np[s, :n] = prop[:n]
            draft_len[s] = n
        return drafts_np, draft_len

    def _spec_draft_device(self, active):
        self._k, self._v, drafts = self._spec.draft(
            self._k, self._v, self._params,
            jnp.asarray(self._lengths), jnp.asarray(self._tokens),
            jnp.asarray(active),
        )
        return drafts

    def _spec_verify_device(self, active, drafts, draft_len):
        self._k, self._v, emitted, acc = self._spec.verify(
            self._k, self._v, self._params,
            jnp.asarray(self._lengths), jnp.asarray(self._tokens),
            jnp.asarray(drafts), jnp.asarray(draft_len),
            jnp.asarray(active), jnp.asarray(self._temps),
            self._rng, np.int32(self._step_idx),
        )
        return emitted, acc

    def _finish(self, req: Request, finished: List[Request]):
        slot = req.slot
        self.scheduler.finish(req)
        if slot >= 0:
            self._release_slot(req, slot)
        finished.append(req)
        self.metrics.requests.inc(
            outcome="truncated" if req.truncated else "finished"
        )
        self.metrics.annotate(
            "serving_finish", rid=req.rid, slot=slot,
            new_tokens=len(req.tokens), truncated=req.truncated,
        )
        self._emit_request_spans(req)

    def _emit_request_spans(self, req: Request, status: str = "ok"):
        """Retrospective phase tree for one terminal request: queue-wait
        / prefill / decode cut at the timestamps the scheduler already
        records, contiguous by construction so their durations sum to
        the request's e2e latency (the §29 trace invariant). Disarmed:
        one global check — zero per-iteration cost in the step loop."""
        tracer = tracing.active_tracer()
        if tracer is None:
            return
        finish = (
            req.finish_ts if req.finish_ts is not None
            else time.monotonic()
        )
        root = tracer.record_span(
            "serving.request", req.submit_ts, finish,
            kind="server", parent=req.trace,
            attrs={
                "rid": req.rid,
                "prompt_len": req.prompt_len,
                "new_tokens": len(req.tokens),
                "truncated": req.truncated,
                "requeues": req.requeues,
                "failure_reason": req.failure_reason,
                "slo_class": req.slo_class,
                "prefix_hit_blocks": req.prefix_hit_blocks,
            },
            status=status,
        )
        if req.admit_ts is None:
            # Never reached a slot (shed / failed while queued): the
            # whole life was queue wait.
            tracer.record_span(
                "serving.queue_wait", req.submit_ts, finish,
                parent=root, status=status,
            )
            return
        tracer.record_span(
            "serving.queue_wait", req.submit_ts, req.admit_ts,
            parent=root,
        )
        if req.first_token_ts is None:
            tracer.record_span(
                "serving.prefill", req.admit_ts, finish,
                parent=root, status=status,
            )
            return
        tracer.record_span(
            "serving.prefill", req.admit_ts, req.first_token_ts,
            parent=root, attrs={"prompt_len": req.prompt_len},
        )
        decode_start = req.first_token_ts
        if req.migrate_end_ts is not None:
            # Migrated request (kvpool/migrate, §36): the migrate
            # window sits between the source-side prefill and the
            # local decode. Decode the SOURCE ran before a live-drain
            # export gets its own contiguous segment so the children
            # still tile the request end to end (the §29 invariant).
            m_end = min(req.migrate_end_ts, finish)
            m_start = req.migrate_start_ts
            if m_start is None or m_start < req.first_token_ts:
                m_start = req.first_token_ts
            m_start = min(m_start, m_end)
            if m_start - req.first_token_ts > 1e-6:
                tracer.record_span(
                    "serving.decode", req.first_token_ts, m_start,
                    parent=root, attrs={"segment": "pre_migrate"},
                )
            tracer.record_span(
                "serving.migrate", m_start, m_end, parent=root,
                attrs={"pause_s": round(m_end - m_start, 6)},
            )
            decode_start = m_end
        decode_span = tracer.record_span(
            "serving.decode", decode_start, finish,
            parent=root, attrs={"new_tokens": len(req.tokens)},
        )
        if req.verify_s > 0.0:
            # Spec decode splits the decode phase into draft / verify
            # sub-spans (per-slot shares of the iteration wall time,
            # laid contiguously — durations are the signal, not the
            # absolute placement).
            td = min(decode_start + req.draft_s, finish)
            tv = min(td + req.verify_s, finish)
            tracer.record_span(
                "serving.decode.draft", decode_start, td,
                parent=decode_span,
                attrs={"spec_drafted": req.spec_drafted},
            )
            tracer.record_span(
                "serving.decode.verify", td, tv,
                parent=decode_span,
                attrs={"spec_accepted": req.spec_accepted},
            )

    def _sync_retrace_metric(self):
        now = self._all_trace_counts()
        delta = sum(now.values()) - sum(self._trace_snapshot.values())
        if delta > 0:
            self.metrics.retraces.inc(delta)
            self._trace_snapshot = dict(now)
