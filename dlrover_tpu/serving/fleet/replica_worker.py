"""Fleet replica subprocess: one ServingEngine behind stdin/stdout JSONL.

Spawned (and respawned after every crash) by
:class:`dlrover_tpu.serving.fleet.replica.SubprocessReplica` — the
``soak_worker`` pattern applied to serving: fault schedules arm from
``DLROVER_TPU_FAULT_SCHEDULE``, fired injections append (fsynced) to
``DLROVER_TPU_FAULT_TRACE`` BEFORE acting, so even this process's own
SIGKILL leaves its trace entry behind.

Protocol (one JSON object per line):

- stdin:  ``{"op": "submit", "request_id", "attempt", "prompt",
  "max_new_tokens", "temperature", "deadline_s"}`` | ``{"op": "stop"}``
  | the §36 migration ops ``import`` / ``export`` / ``release``
  (see :mod:`dlrover_tpu.serving.fleet.replica`)
- stdout: ``{"kind": "ready"}`` once warm, ``{"kind": "heartbeat"}``
  every ``--heartbeat-s`` while serving, one ``{"kind": "done",
  ...}`` completion per accepted (request, attempt) — ok, explicitly
  failed, or shed; never silence — plus ``exported`` / ``imported``
  migration events when serving the paged engine.

The model is the deterministic tiny llama (seed 0), so every replica in
a fleet serves identical weights and a re-routed greedy request decodes
the same tokens on its new replica.
"""

import argparse
import json
import os
import queue
import sys
import threading
import time


def _emit(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _chunk_tokens(engine, prefill_chunk: int) -> int:
    """Prompt tokens the next engine iteration will prefill: the
    FCFS-picked PREFILL slot's next chunk (0 when nothing is
    prefilling) — mirrors the scheduler's one-chunk-per-iteration
    policy, including the short FINAL chunk of a prompt (charging the
    full chunk width for an 8-token tail would tax big-chunk prefill
    tiers for tokens they never compute). Drives the --token-delay-us
    service-time simulation; the decode batch is deliberately NOT
    counted — see the --token-delay-us help for the roofline model."""
    sched = getattr(engine, "scheduler", None)
    by_slot = getattr(sched, "by_slot", None) if sched else None
    if not by_slot:
        return 0
    prefilling = [
        r for r in by_slot if r is not None and r.state == "prefill"
    ]
    if not prefilling:
        return 0
    nxt = min(prefilling, key=lambda r: r.rid)
    return max(min(prefill_chunk, nxt.prompt_len - nxt.prefill_pos), 0)


def _read_commands(q: "queue.Queue[dict]") -> None:
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            q.put(json.loads(line))
        except ValueError:
            continue
    q.put({"op": "stop"})  # parent closed the pipe


def main(argv=None) -> int:
    from dlrover_tpu.serving.scheduler import (
        FLEET_SLO_CLASSES,
        parse_slo_classes,
    )

    parser = argparse.ArgumentParser(description="fleet replica worker")
    parser.add_argument("--replica-id", default="0")
    parser.add_argument("--slots", type=int, default=2)
    parser.add_argument("--max-len", type=int, default=64)
    parser.add_argument("--prefill-chunk", type=int, default=8)
    parser.add_argument("--heartbeat-s", type=float, default=0.2)
    parser.add_argument(
        "--step-delay-ms", type=float, default=0.0,
        help="simulated accelerator milliseconds per engine iteration "
        "(the soak-worker --step-ms idiom): sleeping releases the "
        "host CPU, so a fleet bench on a small host measures the "
        "router/host plane, not the tiny model's CPU decode. In the "
        "roofline service-time model this is the memory-bound term — "
        "the weight/KV read every iteration pays once, which the "
        "whole decode batch rides for free",
    )
    parser.add_argument(
        "--token-delay-us", type=float, default=0.0,
        help="simulated accelerator microseconds per PREFILL token in "
        "the iteration's prompt chunk — the compute-bound roofline "
        "term. Decode is memory-bound (batch amortizes the flat "
        "--step-delay-ms read), prefill is compute-bound (cost grows "
        "with chunk tokens): a mixed replica's chunked iteration "
        "therefore stretches every co-resident decoder's inter-token "
        "latency by the chunk's compute, and a replica that chunks at "
        "4 pays the flat read per 4 prompt tokens while a dedicated "
        "prefill tier chunking at 16 pays it per 16 — the two "
        "interference asymmetries disaggregation (§36) exists to "
        "split apart",
    )
    parser.add_argument(
        "--paged", action="store_true",
        help="serve from the paged block-table engine "
        "(serving/kvpool) instead of the flat slot pool; heartbeats "
        "then carry allocator stats for the block-reclaim invariant",
    )
    parser.add_argument("--block-size", type=int, default=8)
    parser.add_argument(
        "--num-blocks", type=int, default=0,
        help="managed pool size (0 = flat-equivalent: "
        "slots*max_len/block_size + sentinel)",
    )
    parser.add_argument(
        "--slo-classes",
        default=",".join(
            f"{c.name}:{c.weight:g}" for c in FLEET_SLO_CLASSES
        ),
        help='SLO classes as "name:weight,..."; first is the default '
        "for untagged requests. The default is scheduler."
        "FLEET_SLO_CLASSES — a stock replica understands the "
        "conventional interactive/batch split so tagged fleet "
        "traffic is never rejected at the scheduler",
    )
    args = parser.parse_args(argv)

    from dlrover_tpu.fault import arm_from_env, fault_point
    from dlrover_tpu.observability import tracing

    arm_from_env()
    # Same env-rigging pattern for tracing: DLROVER_TPU_TRACE_FILE set
    # by the parent replica handle when the router process traces.
    tracing.arm_from_env(service=f"replica{args.replica_id}")

    import jax

    from dlrover_tpu.models import llama
    from dlrover_tpu.serving.engine import ServingEngine
    from dlrover_tpu.serving.fleet.replica import (
        serve_control,
        serve_exports,
        serve_step,
        serve_submit,
    )

    cfg = llama.tiny_config()
    params, _ = llama.init_params(cfg, jax.random.key(0))
    slo_classes = parse_slo_classes(args.slo_classes)
    if args.paged:
        from dlrover_tpu.serving.kvpool import PagedServingEngine

        engine = PagedServingEngine(
            cfg, params,
            slots=args.slots,
            max_len=args.max_len,
            prefill_chunk=args.prefill_chunk,
            block_size=args.block_size,
            num_blocks=args.num_blocks or None,
            slo_classes=slo_classes,
        )
    else:
        engine = ServingEngine(
            cfg, params,
            slots=args.slots,
            max_len=args.max_len,
            prefill_chunk=args.prefill_chunk,
            slo_classes=slo_classes,
        )
    engine.warmup()

    commands: "queue.Queue[dict]" = queue.Queue()
    reader = threading.Thread(
        target=_read_commands, args=(commands,), daemon=True
    )
    reader.start()

    _emit({"kind": "ready", "replica": args.replica_id,
           "pid": os.getpid()})
    by_rid = {}  # engine rid -> (request_id, attempt)
    migrate_rids = set()  # engine rids flagged for post-prefill export
    last_hb = 0.0
    while True:
        now = time.monotonic()
        if now - last_hb >= args.heartbeat_s:
            try:
                fault_point(
                    "fleet.health.heartbeat", replica=args.replica_id
                )
                beat = {"kind": "heartbeat", "replica": args.replica_id}
                if args.paged:
                    # Allocator accounting rides every beat so block
                    # conservation is checkable THROUGH a crash: the
                    # parent validates at receipt, and a SIGKILLed
                    # replica's last-known stats survive it.
                    beat["kv"] = engine.kv_stats()
                _emit(beat)
                last_hb = now
            except Exception:
                last_hb = now  # dropped beat; try again next window
        try:
            cmd = commands.get(
                timeout=0.0 if engine.pending() else 0.02
            )
        except queue.Empty:
            cmd = None
        if cmd is not None:
            if cmd.get("op") == "stop":
                return 0
            if cmd.get("op") == "submit":
                req = serve_submit(
                    engine, by_rid, _emit,
                    cmd["request_id"], cmd.get("attempt", 0),
                    cmd["prompt"], cmd["max_new_tokens"],
                    cmd.get("temperature", 0.0), cmd.get("deadline_s"),
                    trace=cmd.get("trace"),
                    slo_class=cmd.get("slo_class"),
                )
                if req is not None and cmd.get("migrate_after_prefill"):
                    migrate_rids.add(req.rid)
            elif cmd.get("op") in ("import", "export", "release"):
                if cmd["op"] == "import":
                    # The kill_during_migration chaos window: the
                    # payload has left the source (export done) and no
                    # import ack has been emitted — a ``crash`` rule
                    # here SIGKILLs the destination holding the bytes.
                    # The source was never released, so it must still
                    # complete the request exactly once with zero
                    # blocks lost on either end.
                    fault_point(
                        "fleet.replica.import", replica=args.replica_id
                    )
                serve_control(engine, by_rid, _emit, migrate_rids, cmd)
        if engine.pending():
            # The chaos episode's SIGKILL-mid-decode lands here: a
            # ``crash`` rule on fleet.replica.step fires between two
            # engine iterations with requests live in slots.
            fault_point("fleet.replica.step", replica=args.replica_id)
            delay = args.step_delay_ms / 1000.0
            if args.token_delay_us > 0:
                delay += args.token_delay_us * _chunk_tokens(
                    engine, args.prefill_chunk
                ) / 1e6
            if delay > 0:
                time.sleep(delay)
            serve_step(engine, by_rid, _emit)
        serve_exports(engine, by_rid, _emit, migrate_rids)


if __name__ == "__main__":
    raise SystemExit(main())
