"""Fleet metric families: the router's health/retry/hedge/shed ledger
on the same process registry the master scrapes (/metrics exposition,
observability/registry.py). Registration is idempotent; one process's
routers share families the way engines share serving_* families.
"""

from typing import Optional

from dlrover_tpu.observability.registry import default_registry

_TTFT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)
_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0,
)
_QUEUE_WAIT_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0,
)
_MIGRATION_PAUSE_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 5.0,
)


class FleetMetrics:
    """Handle bundle over the router's registry families."""

    def __init__(self, registry=None):
        reg = registry or default_registry()
        self.replica_state = reg.gauge(
            "fleet_replica_state",
            "per-replica breaker state "
            "(0 healthy, 1 suspect, 2 broken, 3 half_open)",
            labelnames=("replica",),
        )
        self.health_transitions = reg.counter(
            "fleet_health_transitions_total",
            "breaker transitions per replica, by destination state",
            labelnames=("replica", "to"),
        )
        self.requests = reg.counter(
            "fleet_requests_total",
            "router requests by terminal outcome "
            "(accepted, completed, failed, shed)",
            labelnames=("outcome",),
        )
        self.failures = reg.counter(
            "fleet_requests_failed_total",
            "terminally failed requests by machine-readable reason",
            labelnames=("reason",),
        )
        self.dispatches = reg.counter(
            "fleet_dispatches_total",
            "work handed to replicas, by kind "
            "(primary, retry, hedge)",
            labelnames=("kind",),
        )
        self.retries = reg.counter(
            "fleet_retries_total",
            "re-dispatches after a failed attempt (different replica)",
        )
        self.hedges = reg.counter(
            "fleet_hedges_total",
            "speculative duplicate dispatches for slow short requests",
        )
        self.sheds = reg.counter(
            "fleet_sheds_total",
            "requests refused/dropped without dispatch, by reason "
            "(overload, deadline)",
            labelnames=("reason",),
        )
        self.reroutes = reg.counter(
            "fleet_reroutes_total",
            "in-flight attempts reclaimed from a broken replica and "
            "re-queued",
        )
        self.duplicates = reg.counter(
            "fleet_duplicate_completions_total",
            "completions dropped because the request already has a "
            "recorded result (hedges, reclaimed-but-alive attempts)",
        )
        self.stale_completions = reg.counter(
            "fleet_stale_completions_total",
            "completions for an attempt the router already reclaimed, "
            "arriving while the request is still live elsewhere — "
            "dropped, but NOT duplicates: no result existed yet",
        )
        self.restarts = reg.counter(
            "fleet_replica_restarts_total",
            "replica process/thread restarts issued by the router",
        )
        self.affinity_dispatches = reg.counter(
            "fleet_affinity_dispatches_total",
            "dispatches routed by prefix affinity (§31): the request "
            "went to the replica holding its prompt prefix's warm KV "
            "blocks instead of the least-loaded choice",
        )
        self.migrations = reg.counter(
            "fleet_migrations_total",
            "KV-block migrations completed (§36): export on the "
            "source, import acked by the destination, source released",
        )
        self.migration_failures = reg.counter(
            "fleet_migration_failures_total",
            "migrations that fell back, by reason (no_destination, "
            "import_send, refused/import error class, timeout) — the "
            "request still completes exactly once: on the source or "
            "via one from-scratch re-prefill",
            labelnames=("reason",),
        )
        self.queue_depth = reg.gauge(
            "fleet_queue_depth",
            "router requests waiting for a dispatchable replica",
        )
        self.inflight = reg.gauge(
            "fleet_inflight",
            "attempts currently running on replicas",
        )
        self.replicas_dispatchable = reg.gauge(
            "fleet_replicas_dispatchable",
            "replicas the breaker currently admits traffic to",
        )
        self.ttft = reg.histogram(
            "fleet_ttft_seconds",
            "router-submit to first token (queue + dispatch + replica "
            "TTFT)",
            buckets=_TTFT_BUCKETS,
        )
        self.latency = reg.histogram(
            "fleet_request_latency_seconds",
            "router-submit to recorded completion",
            buckets=_LATENCY_BUCKETS,
        )
        self.queue_wait = reg.histogram(
            "fleet_queue_wait_seconds",
            "router-submit to first dispatch",
            buckets=_QUEUE_WAIT_BUCKETS,
        )
        self.migration_pause = reg.histogram(
            "fleet_migration_pause_seconds",
            "export receipt to import ack on the router clock — the "
            "window a migrating request makes no decode progress",
            buckets=_MIGRATION_PAUSE_BUCKETS,
        )


_metrics: Optional[FleetMetrics] = None


def fleet_metrics(registry=None) -> FleetMetrics:
    """Process-wide handle (or a private one for a passed registry)."""
    global _metrics
    if registry is not None:
        return FleetMetrics(registry)
    if _metrics is None:
        _metrics = FleetMetrics()
    return _metrics


def reset_fleet_metrics():
    """Tests only: forget the cached handle."""
    global _metrics
    _metrics = None
