"""Health-gated request router over N serving replicas.

The fleet front door (ROADMAP item 1's robustness half): requests enter
here, replicas die/stall/overload behind it, and the contract to the
caller stays simple — **every accepted request completes or is
explicitly failed exactly once**. The pieces:

- **Health-gated least-loaded dispatch.** Each replica sits behind a
  :class:`~dlrover_tpu.serving.fleet.health.ReplicaHealth` breaker;
  dispatch picks the least-loaded replica the breaker admits (HEALTHY
  before SUSPECT before HALF_OPEN probes). One seeded RNG drives all
  jitter, so a router run is as reproducible as a fault schedule.
- **Deadlines.** A per-request TTL is checked at admission, at every
  pump, and at dispatch; the REMAINING budget is propagated into the
  replica's scheduler (satellite: `Scheduler.shed_expired`), so a dead
  client's request cannot occupy a slot anywhere in the fleet.
- **Bounded jittered retries.** A failed attempt (replica error,
  dispatch fault, replica death) re-dispatches to a *different* replica
  after an exponential jittered backoff, at most ``max_retries`` times;
  exhaustion is an explicit terminal failure carrying the last
  machine-readable reason.
- **At-most-once completion.** The stable ``request_id`` keys a result
  table; the first completion wins and every later one (hedge twin,
  reclaimed-but-alive attempt, replayed wire event) is dropped and
  counted in ``fleet_duplicate_completions_total``.
- **Hedging.** A short request (``max_new <= hedge_max_new_tokens``)
  whose sole attempt has been out longer than the observed service-
  latency percentile gets a speculative duplicate on a different
  replica — tail latency protection that the at-most-once table makes
  safe.
- **Load shedding.** Admission beyond ``max_queue`` returns an explicit
  overload result immediately — the router never queues unboundedly.
- **Crash re-routing.** A replica whose breaker enters BROKEN (process
  exit, poisoned thread, missed heartbeats) has its in-flight ledger
  reclaimed — `Scheduler.requeue_active` semantics lifted to the fleet:
  victims re-queue at the FRONT in submit order and re-dispatch
  elsewhere. The router restarts dead replicas after the breaker's
  cooldown and re-admits them through half-open probes.
- **Disaggregated prefill/decode (§36).** Replica handles carry a
  ``role`` (``prefill`` | ``decode`` | ``mixed``; all-mixed = the
  co-located baseline, byte-for-byte unchanged). Fresh work lands on
  prefill-capable replicas; a request dispatched to a ``prefill``
  replica is flagged ``migrate_after_prefill`` — when its first token
  lands, the replica exports the KV blocks, the router hands them to
  the least-loaded decode-capable replica, and on the import ack moves
  the in-flight ledger entry and releases the source. Every failure
  mode falls back without breaking exactly-once: a refused/failed
  import means the source (still live — it keeps decoding until the
  release ack) completes the request; a destination that dies after
  the ack is the ordinary crash-re-route, one from-scratch re-prefill.
  ``drain_replica`` uses the same machinery to move in-flight decodes
  OFF a shrinking replica instead of requeueing them from zero.

The router is pump-driven by design: every structure is owned by the
pump (``step()``), driven by the caller or by ``serve_forever``-style
loops; replicas do their work on their own threads/processes and
communicate only through their mailboxes. A router-wide RLock
serializes the public surface (``step``/``submit``/``results`` and the
live-sizing verbs ``add_replica``/``drain_replica``) so a §30
autoscaler thread can resize the fleet against a pumping router;
uncontended, the lock is one acquire per pump. With an injected clock
and fake replicas the whole policy surface is unit-testable without
sleeps.
"""

import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point
from dlrover_tpu.observability import tracing
from dlrover_tpu.serving.fleet import health as health_lib
from dlrover_tpu.serving.fleet.metrics import fleet_metrics
from dlrover_tpu.serving.fleet.replica import ReplicaDeadError, WorkItem


@dataclass
class RouterConfig:
    max_queue: int = 256            # admission bound (queued + waiting)
    max_retries: int = 2            # re-dispatches after failed attempts
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    retry_jitter_frac: float = 0.3
    hedge_enabled: bool = False     # optional speculative duplicates
    hedge_max_new_tokens: int = 16  # only short requests hedge
    hedge_after_s: Optional[float] = None   # None = adaptive percentile
    hedge_percentile: float = 95.0
    hedge_min_after_s: float = 0.25
    hedge_min_samples: int = 8      # latencies before adaptive hedging
    default_deadline_s: Optional[float] = None
    max_done_retained: int = 4096   # terminal requests kept for results()
    auto_restart: bool = True       # respawn dead replicas post-cooldown
    # A freshly restarted replica is silent while it boots (subprocess
    # JAX init + warmup can take many seconds): give it this long
    # before heartbeat silence reads as "wedged, restart again" — or a
    # slow boot becomes an infinite restart loop.
    restart_boot_grace_s: float = 30.0
    seed: int = 0
    # Prefix-affinity dispatch (§31): requests whose leading tokens
    # match a recently dispatched prefix prefer the replica holding
    # the warm KV blocks — health gating and at-most-once semantics
    # UNCHANGED (affinity only picks among already-dispatchable
    # candidates, after probe canarying, and never a replica the
    # request already tried).
    prefix_affinity: bool = True
    # Leading tokens hashed as the affinity key: requests sharing at
    # LEAST this many leading tokens route together. Two cache blocks
    # at the default block size — shorter than typical system prompts
    # (keying on more tokens than the shared prefix would fold the
    # divergent tail into the hash and group nothing).
    affinity_prefix_tokens: int = 16
    affinity_max_entries: int = 2048    # bounded LRU prefix -> replica map
    # Affinity yields to load balance when the warm replica is this
    # many in-flight items busier than the least-loaded candidate.
    affinity_max_load_gap: int = 4
    # §36: an exported payload whose import ack never arrives
    # (destination SIGKILLed mid-migration) is forgotten after this
    # long — the source, still live, completes the request.
    migration_timeout_s: float = 30.0
    # Live drain (§36): how long drain_replica pumps for in-flight
    # decodes to migrate off before falling back to requeue-from-zero.
    drain_migrate_timeout_s: float = 10.0
    health: health_lib.HealthPolicy = field(
        default_factory=health_lib.HealthPolicy
    )


@dataclass
class FleetResult:
    request_id: str
    ok: bool
    tokens: List[int] = field(default_factory=list)
    truncated: bool = False
    failure_reason: str = ""
    replica_id: str = ""
    attempts: int = 0
    retries: int = 0
    hedged: bool = False
    ttft_s: Optional[float] = None
    latency_s: Optional[float] = None


@dataclass
class FleetRequest:
    """Router-side request state. ``request_id`` is stable across every
    retry/hedge — it IS the at-most-once key."""

    request_id: str
    seq: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    slo_class: Optional[str] = None       # named SLO class (§31)
    prefix_key: Optional[int] = None      # affinity hash of the prompt head
    deadline: Optional[float] = None      # absolute, router clock
    submit_t: float = 0.0
    accepted: bool = True
    attempt_seq: int = 0                  # next attempt number
    failed_attempts: int = 0
    hedged: bool = False
    first_dispatch_t: Optional[float] = None
    # attempt -> (replica_id, dispatch_t, is_probe)
    live_attempts: Dict[int, Tuple[str, float, bool]] = field(
        default_factory=dict
    )
    tried_replicas: set = field(default_factory=set)
    result: Optional[FleetResult] = None
    # Tracing (None when disarmed): one root span per request, one
    # child span per dispatch attempt — retries and hedges are SIBLING
    # spans under the root, so a rerouted request's tree shows the
    # failed attempt next to the one that won.
    span: Optional[object] = None
    attempt_spans: Dict[int, object] = field(default_factory=dict)

    @property
    def done(self) -> bool:
        return self.result is not None


@dataclass
class _Migration:
    """One in-flight §36 migration: export received from ``src``,
    import sent to ``dst``, awaiting the ack. The source keeps the
    request live the whole time — a lost ack costs nothing but the
    wasted wire bytes."""

    req: FleetRequest
    attempt: int
    src: str
    dst: str
    export_t: float
    span: Optional[object] = None


class FleetRouter:
    """See module docstring. One pump thread drives ``step()``; the
    live-sizing surface (``add_replica``/``drain_replica``, the §30
    autoscaler's actuation path) and ``submit`` may be called from
    OTHER threads — a router-wide RLock serializes them against the
    pump, so a drain can never yank ``_replicas``/``_ledger`` out from
    under a step iteration."""

    def __init__(
        self,
        replicas: List,
        config: Optional[RouterConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self._lock = threading.RLock()
        self.config = config or RouterConfig()
        self._clock = clock
        self.metrics = fleet_metrics(registry)
        self._replicas = {r.replica_id: r for r in replicas}
        if len(self._replicas) != len(replicas):
            raise ValueError("duplicate replica_id in fleet")
        self._health: Dict[str, health_lib.ReplicaHealth] = {}
        for rid in self._replicas:
            self._health[rid] = health_lib.ReplicaHealth(
                rid,
                policy=self.config.health,
                clock=clock,
                on_transition=self._make_transition_hook(rid),
            )
            self.metrics.replica_state.set(0, replica=rid)
        self._queue: Deque[FleetRequest] = deque()
        self._waiting: List[Tuple[float, FleetRequest]] = []
        # replica_id -> {(request_id, attempt) -> FleetRequest}
        self._ledger: Dict[str, Dict[Tuple[str, int], FleetRequest]] = {
            rid: {} for rid in self._replicas
        }
        self._requests: Dict[str, FleetRequest] = {}
        # Terminal requests in completion order; bounds _requests so a
        # long-lived router does not grow RSS with every request ever
        # served (callers keep their own FleetRequest handles).
        self._done_order: Deque[str] = deque()
        # Requests that went terminal OUTSIDE a step() (a drain's
        # reclaim can terminal-fail deadline/budget-exhausted victims):
        # delivered by the NEXT step so run_until_idle's "returns every
        # request that went terminal" contract holds.
        self._orphan_done: List[FleetRequest] = []
        self._live_accepted = 0   # accepted, no terminal result yet
        # prefix hash -> replica_id holding that prefix's warm blocks
        # (bounded LRU; entries for gone replicas lapse on validation).
        self._affinity: "OrderedDict[int, str]" = OrderedDict()
        self._last_restart: Dict[str, float] = {}
        self._service_lat: Deque[float] = deque(maxlen=256)
        # §36: (request_id, attempt) -> in-flight migration awaiting
        # its import ack; replicas being drained (no new dispatches,
        # no migration destinations, no auto-restart).
        self._migrations: Dict[Tuple[str, int], _Migration] = {}
        self._draining: set = set()
        # Keys whose live-drain export failed (flat engine): the drain
        # loop stops waiting on them and falls back to requeue.
        self._export_failed: set = set()
        self._rng = random.Random(self.config.seed)
        self._seq = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self, wait_ready: bool = True,
              timeout_s: float = 120.0) -> None:
        for replica in self._replicas.values():
            replica.start()
        if wait_ready:
            deadline = self._clock() + timeout_s
            for replica in self._replicas.values():
                left = max(0.1, deadline - self._clock())
                if not replica.wait_ready(left):
                    logger.warning(
                        "replica %s not ready within %.0fs",
                        replica.replica_id, timeout_s,
                    )
        now = self._clock()
        for h in self._health.values():
            h.observe_heartbeat(now)

    def stop(self) -> None:
        # Snapshot under the lock (an autoscaler thread may be
        # resizing), stop outside it (subprocess teardown can block).
        with self._lock:
            replicas = list(self._replicas.values())
        for replica in replicas:
            try:
                replica.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass

    # ---- live fleet sizing (the §30 autoscaler's actuation surface) --------

    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def add_replica(self, replica, start: bool = True,
                    wait_ready: bool = False,
                    timeout_s: float = 120.0) -> None:
        """Grow the fleet live: register (and by default start) a new
        replica. It enters HEALTHY with a fresh heartbeat — the boot
        grace the breaker's missed-heartbeat strikes then cover, the
        same contract a restart gets."""
        rid = replica.replica_id
        with self._lock:
            if rid in self._replicas:
                raise ValueError(f"duplicate replica_id {rid!r}")
        # Boot OUTSIDE the router lock: a subprocess replica's start is
        # seconds of interpreter/JAX init — holding the lock would
        # freeze the pump (and every in-flight request) for the whole
        # boot, exactly during the overload a GROW decision answers.
        if start:
            replica.start()
            if wait_ready and not replica.wait_ready(timeout_s):
                # The caller asked to block until serving: a boot
                # timeout must surface, not register a mute replica
                # as HEALTHY.
                try:
                    replica.stop()
                except Exception:  # noqa: BLE001 — best-effort
                    pass
                raise TimeoutError(
                    f"replica {rid} not ready within {timeout_s:.0f}s"
                )
        with self._lock:
            if rid in self._replicas:
                # Lost a register race while booting: this instance is
                # surplus, not fleet state.
                if start:
                    try:
                        replica.stop()
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                raise ValueError(f"duplicate replica_id {rid!r}")
            self._replicas[rid] = replica
            self._ledger[rid] = {}
            self._health[rid] = health_lib.ReplicaHealth(
                rid,
                policy=self.config.health,
                clock=self._clock,
                on_transition=self._make_transition_hook(rid),
            )
            self.metrics.replica_state.set(0, replica=rid)
            self._health[rid].observe_heartbeat(self._clock())
            logger.info("fleet replica %s added (%d replicas)",
                        rid, len(self._replicas))

    def drain_replica(self, replica_id, stop: bool = True,
                      migrate: bool = True) -> bool:
        """Shrink the fleet live. With ``migrate`` (§36, the default)
        in-flight decodes are first MIGRATED off — each one keeps its
        sampled tokens and filled blocks instead of re-prefilling from
        zero; whatever cannot migrate within
        ``drain_migrate_timeout_s`` (mid-prefill, flat engine, no
        destination) falls back to the crash-re-route path, so nothing
        is lost or duplicated either way. The replica is fenced from
        new dispatches and destinations for the whole drain. Refuses
        to drain the last replica — a fleet of zero is an outage, not
        a scale decision."""
        rid = str(replica_id)
        migrating = False
        with self._lock:
            if rid not in self._replicas:
                return False
            if len(self._replicas) <= 1:
                raise ValueError(
                    "refusing to drain the last fleet replica"
                )
            self._draining.add(rid)
            replica = self._replicas[rid]
            if migrate and self._ledger[rid] and replica.alive():
                for request_id, attempt in list(self._ledger[rid]):
                    try:
                        replica.send({
                            "op": "export",
                            "request_id": request_id,
                            "attempt": attempt,
                        })
                        migrating = True
                    except Exception:  # noqa: BLE001 — no send()
                        # surface / dead pipe: requeue-from-zero below.
                        break
        if migrating:
            # Pump OUTSIDE the lock until every in-flight key either
            # migrated away (ledger entry moved to its destination),
            # finished, or declared itself unexportable.
            deadline = (
                time.monotonic() + self.config.drain_migrate_timeout_s
            )
            while time.monotonic() < deadline:
                self.step()
                with self._lock:
                    waiting = any(
                        k not in self._export_failed
                        for k in self._ledger.get(rid, {})
                    ) or any(
                        m.src == rid
                        for m in self._migrations.values()
                    )
                if not waiting:
                    break
                time.sleep(0.002)
        with self._lock:
            self._export_failed.clear()
            self._draining.discard(rid)
            if rid not in self._replicas:
                return False  # lost a drain race
            now = self._clock()
            newly_done: List[FleetRequest] = []
            # Whatever still sits on the replica re-queues from zero —
            # the crash-re-route path.
            self._reclaim(rid, now, newly_done)
            # Terminal results produced by the reclaim surface from the
            # next step(), not silently only in results().
            self._orphan_done.extend(newly_done)
            replica = self._replicas.pop(rid)
            self._health.pop(rid, None)
            self._ledger.pop(rid, None)
            self._last_restart.pop(rid, None)
            self._purge_affinity(rid)
            remaining = len(self._replicas)
        if stop:
            try:
                replica.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        logger.info("fleet replica %s drained (%d replicas remain)",
                    rid, remaining)
        return True

    # ---- submission --------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float = 0.0,
        deadline_s: Optional[float] = None,
        request_id: Optional[str] = None,
        slo_class: Optional[str] = None,
    ) -> FleetRequest:
        with self._lock:
            return self._submit_locked(
                prompt, max_new_tokens, temperature, deadline_s,
                request_id, slo_class,
            )

    def _submit_locked(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float,
        deadline_s: Optional[float],
        request_id: Optional[str],
        slo_class: Optional[str] = None,
    ) -> FleetRequest:
        now = self._clock()
        self._seq += 1
        if request_id is None:
            request_id = f"req-{self._seq}"
        if request_id in self._requests:
            raise ValueError(f"duplicate request_id {request_id!r}")
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            # Same contract as Scheduler.submit: 0 must not silently
            # mean "no deadline" — that is the opposite of the intent.
            raise ValueError("deadline_s must be positive")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        prefix_key = None
        if self.config.prefix_affinity:
            prefix_key = hash(
                tuple(prompt[:self.config.affinity_prefix_tokens])
            )
        req = FleetRequest(
            request_id=request_id,
            seq=self._seq,
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            slo_class=slo_class,
            prefix_key=prefix_key,
            deadline=(
                now + deadline_s if deadline_s is not None else None
            ),
            submit_t=now,
        )
        self._requests[request_id] = req
        if len(self._queue) + len(self._waiting) >= self.config.max_queue:
            # Explicit overload result, never an unbounded queue: the
            # caller can back off / balance, a dead queue cannot.
            req.accepted = False
            req.result = FleetResult(
                request_id=request_id, ok=False,
                failure_reason="overload",
            )
            self.metrics.sheds.inc(reason="overload")
            self.metrics.requests.inc(outcome="shed")
            self.metrics.failures.inc(reason="overload")
            self._retain_done(request_id)
            return req
        self.metrics.requests.inc(outcome="accepted")
        self._live_accepted += 1
        tracer = tracing.active_tracer()
        if tracer is not None:
            req.span = tracer.start_span(
                "fleet.request", kind="server",
                attrs={
                    "request_id": request_id,
                    "max_new_tokens": req.max_new_tokens,
                    "prompt_len": len(prompt),
                },
            )
        self._queue.append(req)
        self.metrics.queue_depth.set(
            len(self._queue) + len(self._waiting)
        )
        return req

    # ---- the pump ----------------------------------------------------------

    def step(self) -> List[FleetRequest]:
        """One router iteration: drain replica mailboxes, advance
        health, reclaim/re-route, shed expired, dispatch, hedge.
        Returns requests that became terminal THIS call."""
        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> List[FleetRequest]:
        now = self._clock()
        newly_done: List[FleetRequest] = list(self._orphan_done)
        self._orphan_done.clear()
        self._drain_replicas(now, newly_done)
        self._check_replicas(now, newly_done)
        # restart() above can block for seconds (subprocess teardown):
        # deadline math below must not run on a stale clock or expired
        # requests dispatch with phantom budget.
        now = self._clock()
        self._promote_waiting(now)
        self._shed_expired(now, newly_done)
        self._prune_migrations(now)
        self._dispatch_queued(now, newly_done)
        if self.config.hedge_enabled:
            self._hedge_sweep(now, newly_done)
        self.metrics.queue_depth.set(
            len(self._queue) + len(self._waiting)
        )
        self.metrics.inflight.set(
            sum(len(led) for led in self._ledger.values())
        )
        # State reads only — dispatchable(now) would flip a cooled-down
        # BROKEN breaker to HALF_OPEN as a side effect.
        self.metrics.replicas_dispatchable.set(sum(
            1 for rid, replica in self._replicas.items()
            if replica.alive()
            and self._health[rid].state != health_lib.BROKEN
        ))
        return newly_done

    def pending(self) -> int:
        """Accepted requests without a terminal result. O(1): this is
        polled every pump by run_until_idle and the soak/bench loops,
        and _requests retains up to max_done_retained terminal entries."""
        return self._live_accepted

    def run_until_idle(self, timeout_s: float = 120.0,
                       idle_sleep_s: float = 0.002) -> List[FleetRequest]:
        """Pump until nothing is pending (or timeout); returns every
        request that went terminal during the run."""
        done: List[FleetRequest] = []
        deadline = time.monotonic() + timeout_s
        while self.pending():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet did not drain within {timeout_s}s: "
                    f"{self.pending()} pending"
                )
            got = self.step()
            done.extend(got)
            if not got:
                time.sleep(idle_sleep_s)
        return done

    def results(self) -> Dict[str, FleetResult]:
        with self._lock:
            return {
                rid: r.result
                for rid, r in self._requests.items()
                if r.result is not None
            }

    def health_state(self, replica_id: str) -> str:
        with self._lock:
            return self._health[str(replica_id)].state

    # ---- completions -------------------------------------------------------

    def _drain_replicas(self, now: float, newly_done: List[FleetRequest]):
        for rid, replica in list(self._replicas.items()):
            self._health[rid].observe_heartbeat(replica.last_heartbeat())
            for event in replica.poll():
                kind = event.get("kind")
                if kind == "done":
                    self._handle_completion(rid, event, now, newly_done)
                elif kind == "exported":
                    self._handle_exported(rid, event, now)
                elif kind == "imported":
                    self._handle_imported(rid, event, now)

    def _handle_completion(self, rid: str, event: dict, now: float,
                           newly_done: List[FleetRequest]):
        request_id = event.get("request_id")
        attempt = event.get("attempt", 0)
        req = self._requests.get(request_id)
        key = (request_id, attempt)
        entry = self._ledger[rid].pop(key, None)
        if req is None or req.done:
            # At-most-once: the result table already holds this
            # request's terminal outcome (hedge twin finished first, or
            # the attempt was reclaimed and re-run elsewhere).
            self.metrics.duplicates.inc()
            return
        live = req.live_attempts.pop(attempt, None)
        aspan = req.attempt_spans.pop(attempt, None)
        if live is not None and live[2]:
            self._health[rid].end_probe()
        if entry is None and live is None:
            # Attempt already reclaimed (the replica broke, its ledger
            # was re-routed) but the request is still live elsewhere:
            # stale evidence, not a completion — and not a duplicate,
            # since no result has been recorded yet.
            self.metrics.stale_completions.inc()
            return
        dispatch_t = live[1] if live is not None else req.submit_t
        if aspan is not None:
            if not event.get("ok"):
                aspan.set_attr(
                    "failure_reason",
                    event.get("failure_reason") or "replica_error",
                )
            aspan.end(
                status="ok" if event.get("ok") else "error"
            )
        if event.get("ok"):
            self._service_lat.append(max(0.0, now - dispatch_t))
            self._health[rid].record_success()
            self._record_result(req, FleetResult(
                request_id=request_id,
                ok=True,
                tokens=list(event.get("tokens", ())),
                truncated=bool(event.get("truncated")),
                replica_id=rid,
                attempts=req.attempt_seq,
                retries=req.failed_attempts,
                hedged=req.hedged,
                ttft_s=(
                    (dispatch_t - req.submit_t) + event["ttft_s"]
                    if event.get("ttft_s") is not None else None
                ),
                latency_s=now - req.submit_t,
            ), newly_done)
        else:
            reason = event.get("failure_reason") or "replica_error"
            if reason == "rejected":
                # The engine's scheduler refused the request (prompt too
                # long, no decode room): deterministic — every replica
                # would reject it identically, so fail it now instead of
                # burning retries, and strike nobody's breaker.
                self._terminal_failure(req, reason, now, newly_done)
                return
            if reason != "deadline":
                # A replica shedding an expired request is the replica
                # WORKING (client-side condition) — only real errors
                # strike its breaker.
                self._health[rid].record_failure(reason)
            self._attempt_failed(req, reason, now, newly_done)

    def _record_result(self, req: FleetRequest, result: FleetResult,
                       newly_done: List[FleetRequest]):
        req.result = result
        if req.accepted:
            self._live_accepted -= 1
        if result.ok:
            self.metrics.requests.inc(outcome="completed")
            if result.ttft_s is not None:
                self.metrics.ttft.observe(result.ttft_s)
            if result.latency_s is not None:
                self.metrics.latency.observe(result.latency_s)
        # Forget every other live attempt (hedge twin still computing
        # somewhere): its eventual completion is a counted duplicate.
        for attempt, (rid, _t, is_probe) in list(req.live_attempts.items()):
            self._ledger[rid].pop((req.request_id, attempt), None)
            if is_probe:
                self._health[rid].end_probe()
        req.live_attempts.clear()
        for aspan in req.attempt_spans.values():
            aspan.set_attr("abandoned", True)
            aspan.end(status="error")
        req.attempt_spans.clear()
        if req.span is not None:
            req.span.set_attr("retries", result.retries)
            req.span.set_attr("hedged", result.hedged)
            if result.replica_id:
                req.span.set_attr("replica", result.replica_id)
            if not result.ok:
                req.span.set_attr(
                    "failure_reason", result.failure_reason
                )
            req.span.end(status="ok" if result.ok else "error")
            req.span = None
        newly_done.append(req)
        self._retain_done(req.request_id)

    def _retain_done(self, request_id: str) -> None:
        self._done_order.append(request_id)
        while len(self._done_order) > self.config.max_done_retained:
            self._requests.pop(self._done_order.popleft(), None)

    # ---- §36 migration (two-phase dispatch / live drain) -------------------

    def _role(self, rid: str) -> str:
        replica = self._replicas.get(rid)
        return getattr(replica, "role", "mixed") if replica else "mixed"

    def _send_release(self, rid: str, key: Tuple[str, int]) -> None:
        """Ack the source: drop its copy. Best-effort — a dead source
        frees everything at exit anyway."""
        replica = self._replicas.get(rid)
        if replica is None:
            return
        try:
            replica.send({
                "op": "release",
                "request_id": key[0], "attempt": key[1],
            })
        except Exception:  # noqa: BLE001 — dead pipe = moot release
            pass

    def _pick_decode_replica(self, now: float,
                             exclude=()) -> Optional[str]:
        """Least-loaded decode-capable destination for a migration:
        ``decode`` before ``mixed`` (a dedicated decode replica is the
        point of the topology), HEALTHY before SUSPECT before
        HALF_OPEN, then load. Never the source, never a draining
        replica."""
        rank = {
            health_lib.HEALTHY: 0,
            health_lib.SUSPECT: 1,
            health_lib.HALF_OPEN: 2,
        }
        cands = []
        for rid, replica in self._replicas.items():
            if rid in exclude or rid in self._draining:
                continue
            if self._role(rid) not in ("decode", "mixed"):
                continue
            if not replica.alive() or not replica.wait_ready(0.0):
                continue
            h = self._health[rid]
            if not h.dispatchable(now):
                continue
            cands.append((
                0 if self._role(rid) == "decode" else 1,
                rank[h.state], len(self._ledger[rid]), rid,
            ))
        if not cands:
            return None
        cands.sort()
        return cands[0][3]

    def _handle_exported(self, src: str, event: dict, now: float):
        """A source replica exported a flagged request's KV blocks:
        pick a decode destination and forward the payload. No viable
        destination (or a dead pipe) is a FALLBACK, not a failure —
        the source still owns the request and completes it."""
        key = (event.get("request_id"), event.get("attempt", 0))
        req = self._requests.get(key[0])
        if not event.get("payload"):
            # The source could not serialize (flat engine, torn
            # state): it keeps decoding; a drain stops waiting.
            self._export_failed.add(key)
            self.metrics.migration_failures.inc(reason="export_failed")
            return
        if req is None or req.done:
            # Finished/expired while the export was in flight: the
            # source copy is surplus.
            self._send_release(src, key)
            return
        if key in self._migrations:
            return  # duplicate export event (restarted source replays)
        dst = self._pick_decode_replica(now, exclude={src})
        if dst is None:
            self.metrics.migration_failures.inc(reason="no_destination")
            return
        mspan = None
        tracer = tracing.active_tracer()
        if tracer is not None and req.span is not None:
            mspan = tracer.start_span(
                "fleet.migrate", kind="client", parent=req.span,
                attrs={"src": src, "dst": dst},
            )
        try:
            fault_point(
                "fleet.router.migrate",
                src=src, dst=dst, request=key[0],
            )
            self._replicas[dst].send({
                "op": "import",
                "request_id": key[0], "attempt": key[1],
                "payload": event["payload"],
            })
        except Exception as e:  # noqa: BLE001 — dead pipe / injected
            if mspan is not None:
                mspan.set_attr("failure_reason", "import_send")
                mspan.end(status="error")
            self._health[dst].record_failure(
                f"migrate_send:{type(e).__name__}"
            )
            self.metrics.migration_failures.inc(reason="import_send")
            return
        self._migrations[key] = _Migration(
            req=req, attempt=key[1], src=src, dst=dst,
            export_t=now, span=mspan,
        )

    def _handle_imported(self, dst: str, event: dict, now: float):
        """The destination acked an import. ok: move the in-flight
        ledger entry src -> dst, release the source, count the pause.
        not-ok (full destination, flat engine, torn payload): the
        source keeps the request — completion still happens exactly
        once, just co-located."""
        key = (event.get("request_id"), event.get("attempt", 0))
        mig = self._migrations.pop(key, None)
        if mig is None or mig.dst != dst:
            return  # timed-out / already-resolved migration: stale ack
        req = mig.req
        if not event.get("ok"):
            reason = event.get("reason") or "import_failed"
            self.metrics.migration_failures.inc(reason=reason)
            if mig.span is not None:
                mig.span.set_attr("failure_reason", reason)
                mig.span.end(status="error")
            # A refusal is the destination WORKING (it is full, or not
            # paged) — no breaker strike; the source decodes on.
            return
        pause = max(0.0, now - mig.export_t)
        self.metrics.migrations.inc()
        self.metrics.migration_pause.observe(pause)
        self._health[dst].record_success()
        if mig.span is not None:
            mig.span.set_attr("pause_s", round(pause, 6))
            mig.span.end()
        if req.done:
            # The source finished (or the deadline fired) during the
            # handshake: the destination's copy will complete as a
            # counted duplicate; nothing to move.
            self._send_release(mig.src, key)
            return
        entry = self._ledger.get(mig.src, {}).pop(key, None)
        if entry is not None and dst in self._ledger:
            self._ledger[dst][key] = req
            live = req.live_attempts.get(mig.attempt)
            if live is not None:
                if live[2]:
                    # The probe resolved: the source survived an
                    # end-to-end prefill + export.
                    self._health[mig.src].end_probe()
                    self._health[mig.src].record_success()
                req.live_attempts[mig.attempt] = (dst, live[1], False)
            aspan = req.attempt_spans.get(mig.attempt)
            if aspan is not None:
                aspan.set_attr("migrated_to", dst)
        self._send_release(mig.src, key)

    def _prune_migrations(self, now: float):
        """Forget migrations whose ack never came (destination died
        between export and import-ack — the chaos episode). The source
        never released, so the request completes there; zero blocks
        are lost on either end."""
        if not self._migrations:
            return
        expired = [
            k for k, m in self._migrations.items()
            if now - m.export_t > self.config.migration_timeout_s
            or m.req.done
        ]
        for k in expired:
            mig = self._migrations.pop(k)
            reason = "abandoned" if mig.req.done else "timeout"
            if not mig.req.done:
                self.metrics.migration_failures.inc(reason="timeout")
            if mig.span is not None:
                mig.span.set_attr("failure_reason", reason)
                mig.span.end(status="error")

    # ---- failure / retry ---------------------------------------------------

    def _attempt_failed(self, req: FleetRequest, reason: str, now: float,
                        newly_done: List[FleetRequest],
                        immediate: bool = False):
        """One attempt of ``req`` is gone (error, dispatch fault, or
        replica death). Decide: wait for a live twin, retry elsewhere,
        or terminal-fail with the machine-readable reason."""
        req.failed_attempts += 1
        if req.live_attempts:
            return  # a hedge twin is still running; it may yet win
        if req.deadline is not None and now > req.deadline:
            self._terminal_failure(req, "deadline", now, newly_done)
            return
        if req.failed_attempts > self.config.max_retries:
            self._terminal_failure(req, reason, now, newly_done)
            return
        self.metrics.retries.inc()
        if immediate:
            # Crash re-route: no backoff (the failure was the replica,
            # not the request) — FRONT of the queue, oldest first, the
            # fleet analogue of Scheduler.requeue_active.
            self._queue.appendleft(req)
        else:
            backoff = min(
                self.config.retry_backoff_s
                * (2 ** (req.failed_attempts - 1)),
                self.config.retry_backoff_max_s,
            )
            jitter = self.config.retry_jitter_frac
            backoff *= self._rng.uniform(1.0 - jitter, 1.0 + jitter)
            self._waiting.append((now + backoff, req))

    def _terminal_failure(self, req: FleetRequest, reason: str,
                          now: float, newly_done: List[FleetRequest]):
        self._record_result(req, FleetResult(
            request_id=req.request_id,
            ok=False,
            failure_reason=reason,
            attempts=req.attempt_seq,
            retries=req.failed_attempts,
            hedged=req.hedged,
            latency_s=now - req.submit_t,
        ), newly_done)
        self.metrics.requests.inc(outcome="failed")
        self.metrics.failures.inc(reason=reason)

    # ---- health / reclaim --------------------------------------------------

    def _purge_affinity(self, rid: str) -> None:
        """Drop every affinity entry pointing at ``rid`` — its warm
        blocks are gone (drained, crashed, restarted into a cold
        cache). Lazy lapse-on-lookup alone leaves a bounded-LRU slot
        wasted per stale entry AND, worse, keeps steering same-prefix
        requests through a pointless miss path; the eager purge keeps
        the map honest at the moment the blocks die."""
        stale = [k for k, v in self._affinity.items() if v == rid]
        for k in stale:
            self._affinity.pop(k, None)

    def _make_transition_hook(self, rid: str):
        def hook(old: str, new: str):
            self.metrics.replica_state.set(
                health_lib.STATE_CODE[new], replica=rid
            )
            self.metrics.health_transitions.inc(replica=rid, to=new)
            logger.info(
                "fleet replica %s health: %s -> %s", rid, old, new
            )
        return hook

    def _check_replicas(self, now: float,
                        newly_done: List[FleetRequest]):
        for rid, replica in self._replicas.items():
            h = self._health[rid]
            if not replica.alive() and h.state != health_lib.BROKEN:
                h.mark_dead(
                    "process_exit" if replica.mode == "subprocess"
                    else "thread_exit"
                )
            else:
                h.check(now)
            if h.state == health_lib.BROKEN and self._ledger[rid]:
                self._reclaim(rid, now, newly_done)
            # A BROKEN replica with stale heartbeats is WEDGED (hung in
            # a step, not erroring): probes would only oscillate it
            # BROKEN<->HALF_OPEN forever, so it gets the dead-replica
            # remedy. A BROKEN-but-heartbeating replica recovers via
            # probes instead.
            wedged = (
                h.state == health_lib.BROKEN
                and h.heartbeat_age(now)
                > self.config.health.heartbeat_timeout_s
                and now - self._last_restart.get(rid, float("-inf"))
                > self.config.restart_boot_grace_s
            )
            if (
                self.config.auto_restart
                and rid not in self._draining
                and (not replica.alive() or wedged)
                and h.cooldown_elapsed(now)
                # BROKEN keeps its original _broken_since across a
                # failed restart, so cooldown_elapsed stays true; pace
                # respawns explicitly or a crash-on-start replica is
                # forked on every pump.
                and now - self._last_restart.get(rid, float("-inf"))
                >= self.config.health.probe_cooldown_s
            ):
                logger.warning(
                    "fleet replica %s %s past cooldown; restarting",
                    rid, "wedged" if replica.alive() else "dead",
                )
                replica.restart()
                self._last_restart[rid] = now
                self.metrics.restarts.inc()
                # The respawn boots with a cold block cache: affinity
                # entries naming it steer nothing warm anymore.
                self._purge_affinity(rid)
                # Grace: strikes resume from the restart, and the
                # HALF_OPEN flip happens at the next dispatch attempt.
                h.observe_heartbeat(now)

    def _reclaim(self, rid: str, now: float,
                 newly_done: List[FleetRequest]):
        """The fleet's `requeue_active`: pull every in-flight attempt
        off a broken replica and re-route, front-of-queue, in submit
        order."""
        entries = list(self._ledger[rid].items())
        self._ledger[rid].clear()
        self._purge_affinity(rid)
        victims: List[FleetRequest] = []
        for (request_id, attempt), req in entries:
            if req.done:
                continue
            live = req.live_attempts.pop(attempt, None)
            if live is not None and live[2]:
                self._health[rid].end_probe()
            aspan = req.attempt_spans.pop(attempt, None)
            if aspan is not None:
                # The failed attempt stays in the trace as an error
                # sibling of whatever retry eventually wins.
                aspan.set_attr("failure_reason", "replica_death")
                aspan.end(status="error")
            victims.append(req)
            self.metrics.reroutes.inc()
        # Reversed submit order + appendleft = oldest ends up first;
        # _attempt_failed(immediate=True) does the appendleft.
        for req in sorted(victims, key=lambda r: r.seq, reverse=True):
            self._attempt_failed(
                req, "replica_death", now, newly_done, immediate=True
            )

    # ---- dispatch ----------------------------------------------------------

    def _promote_waiting(self, now: float):
        if not self._waiting:
            return
        still = []
        ready = []
        for not_before, req in self._waiting:
            if req.done:
                continue
            (ready if now >= not_before else still).append(
                (not_before, req)
            )
        self._waiting = still
        for _t, req in sorted(ready, key=lambda e: e[1].seq):
            self._queue.append(req)

    def _shed_expired(self, now: float,
                      newly_done: List[FleetRequest]):
        for pool in (
            list(self._queue),
            [r for _t, r in self._waiting],
        ):
            expired = [
                r for r in pool
                if r.deadline is not None and now > r.deadline
                and not r.done
            ]
            if not expired:
                continue
            for req in expired:
                self.metrics.sheds.inc(reason="deadline")
                self._terminal_failure(req, "deadline", now, newly_done)
        if any(r.done for r in self._queue):
            self._queue = deque(
                r for r in self._queue if not r.done
            )
        if any(r.done for _t, r in self._waiting):
            self._waiting = [
                (t, r) for t, r in self._waiting if not r.done
            ]

    def _pick_replica(self, now: float, exclude=(),
                      strict_exclude: bool = False) -> Optional[str]:
        """Least-loaded among breaker-admitted replicas, preferring
        HEALTHY over SUSPECT over HALF_OPEN, and replicas the request
        has not tried. Returns None when nothing is dispatchable."""
        rank = {
            health_lib.HEALTHY: 0,
            health_lib.SUSPECT: 1,
            health_lib.HALF_OPEN: 2,
        }

        def candidates(excluded, allow_decode_role=False):
            cands = []
            for rid in self._replicas:
                if rid in excluded or rid in self._draining:
                    continue
                if (
                    not allow_decode_role
                    and self._role(rid) == "decode"
                ):
                    # §36: dedicated decode replicas take work only
                    # through migration imports — a fresh prompt there
                    # would burn their decode slots on prefill.
                    continue
                if not self._replicas[rid].alive():
                    # Checked BEFORE dispatchable(): a cooled-down dead
                    # replica must neither flip to HALF_OPEN here nor
                    # mask the fall-back to an already-tried live one.
                    continue
                if not self._replicas[rid].wait_ready(0.0):
                    continue  # respawned, still booting
                h = self._health[rid]
                if not h.dispatchable(now):
                    continue
                cands.append(
                    (rank[h.state], len(self._ledger[rid]), rid)
                )
            return cands

        cands = candidates(set(exclude))
        if not cands and exclude and not strict_exclude:
            # Every untried replica is fenced; a retry on a previously
            # tried one beats stalling forever.
            cands = candidates(set())
        if not cands:
            # Availability beats role purity: a fleet whose every
            # prefill-capable replica is down still serves from the
            # decode pool rather than stalling the queue.
            cands = candidates(
                set() if not strict_exclude else set(exclude),
                allow_decode_role=True,
            )
        if not cands:
            return None
        cands.sort()
        return cands[0][2]

    def _pick_probe_replica(self, now: float) -> Optional[str]:
        """A HALF_OPEN (or cooled-down BROKEN) replica with a free
        probe slot. Probes must be actively FED: least-loaded choice
        alone would starve a recovering replica forever while any
        healthy peer exists, so fresh requests canary it explicitly."""
        for rid, replica in self._replicas.items():
            h = self._health[rid]
            if h.state not in (health_lib.BROKEN, health_lib.HALF_OPEN):
                continue
            if rid in self._draining or self._role(rid) == "decode":
                # Decode-role replicas are probed by migration traffic
                # (the import ack records their success), not by fresh
                # prompts.
                continue
            if not replica.alive():
                continue
            if not replica.wait_ready(0.0):
                # Respawned but still booting (JAX init + warmup): a
                # probe now would just sit out the boot while healthy
                # peers idle. Readiness is per-generation, so this
                # self-clears once the replica announces ready.
                continue
            if h.dispatchable(now) and h.is_probe_dispatch():
                return rid
        return None

    def _pick_affinity_replica(self, req: FleetRequest,
                               now: float) -> Optional[str]:
        """The replica that last served this prompt prefix, if it is
        still a LEGITIMATE candidate: alive, ready, breaker-admitted,
        untried by this request, and not more than
        ``affinity_max_load_gap`` in-flight items busier than the
        least-loaded dispatchable peer. Health gating is unchanged —
        affinity only biases the choice among admitted replicas."""
        key = req.prefix_key
        if key is None:
            return None
        rid = self._affinity.get(key)
        if rid is None:
            return None
        replica = self._replicas.get(rid)
        if replica is None:
            self._affinity.pop(key, None)   # drained/removed replica
            return None
        if (
            rid in req.tried_replicas
            or rid in self._draining
            or self._role(rid) == "decode"
            or not replica.alive()
            or not replica.wait_ready(0.0)
            or not self._health[rid].dispatchable(now)
            or self._health[rid].state == health_lib.BROKEN
        ):
            return None
        loads = [
            len(self._ledger[r]) for r in self._replicas
            if self._replicas[r].alive()
            and self._health[r].state != health_lib.BROKEN
        ]
        if loads and (
            len(self._ledger[rid]) - min(loads)
            > self.config.affinity_max_load_gap
        ):
            return None   # warm blocks are not worth a hot spot
        return rid

    def _dispatch_queued(self, now: float,
                         newly_done: List[FleetRequest]):
        stalled: List[FleetRequest] = []
        while self._queue:
            req = self._queue.popleft()
            if req.done:
                continue
            rid = None
            if not req.failed_attempts:
                # Only fresh requests canary a recovering replica —
                # a retried request has already paid a failed attempt
                # and goes to the best-known replica.
                rid = self._pick_probe_replica(now)
            affine = False
            if rid is None and self.config.prefix_affinity:
                rid = self._pick_affinity_replica(req, now)
                affine = rid is not None
            if rid is None:
                rid = self._pick_replica(
                    now, exclude=req.tried_replicas
                )
            if rid is None:
                stalled.append(req)
                break
            kind = "retry" if req.failed_attempts else "primary"
            if self._dispatch(req, rid, kind, now, newly_done) and affine:
                self.metrics.affinity_dispatches.inc()
        # Preserve order for everything not dispatched this pump.
        for req in reversed(stalled):
            self._queue.appendleft(req)

    def _dispatch(self, req: FleetRequest, rid: str, kind: str,
                  now: float, newly_done: List[FleetRequest]) -> bool:
        h = self._health[rid]
        is_probe = h.is_probe_dispatch()
        attempt = req.attempt_seq
        deadline_s = None
        if req.deadline is not None:
            deadline_s = max(0.001, req.deadline - now)
        aspan = None
        tracer = tracing.active_tracer()
        if tracer is not None and req.span is not None:
            aspan = tracer.start_span(
                "fleet.attempt", kind="client", parent=req.span,
                attrs={"replica": rid, "kind": kind,
                       "attempt": attempt},
            )
        # §36: work landing on a dedicated prefill replica is flagged
        # for post-prefill export — provided a decode-capable peer
        # exists to receive it (re-checked at export time; a vanished
        # peer just means the prefill replica decodes this one itself).
        migrate = self._role(rid) == "prefill" and any(
            r != rid and r not in self._draining
            and self._role(r) in ("decode", "mixed")
            and self._replicas[r].alive()
            for r in self._replicas
        )
        item = WorkItem(
            request_id=req.request_id,
            attempt=attempt,
            prompt=req.prompt,
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature,
            deadline_s=deadline_s,
            slo_class=req.slo_class,
            trace=aspan.carrier() if aspan is not None else None,
            migrate_after_prefill=migrate,
        )
        try:
            fault_point(
                "fleet.router.dispatch",
                replica=rid, request=req.request_id,
            )
            self._replicas[rid].submit(item)
        except Exception as e:  # noqa: BLE001 — ReplicaDeadError,
            # injected dispatch faults, broken pipes: all one path.
            if aspan is not None:
                aspan.set_attr("failure_reason", "dispatch_error")
                aspan.end(status="error")
            h.record_failure(f"dispatch:{type(e).__name__}")
            # The replica was tried and failed us — without this the
            # retry's least-loaded sort can deterministically pick the
            # SAME replica again (rank/load ties break on rid).
            req.tried_replicas.add(rid)
            if kind == "hedge":
                # The primary attempt is live and untouched: a hedge
                # that never dispatched cancels itself without charging
                # the request's retry budget.
                return False
            self._attempt_failed(
                req, "dispatch_error", now, newly_done
            )
            return False
        req.attempt_seq += 1
        req.tried_replicas.add(rid)
        if req.prefix_key is not None:
            # This replica now holds (or is prefilling) the prefix's
            # blocks: later same-prefix requests prefer it.
            self._affinity[req.prefix_key] = rid
            self._affinity.move_to_end(req.prefix_key)
            while len(self._affinity) > self.config.affinity_max_entries:
                self._affinity.popitem(last=False)
        if aspan is not None:
            req.attempt_spans[attempt] = aspan
        if req.first_dispatch_t is None:
            req.first_dispatch_t = now
            self.metrics.queue_wait.observe(now - req.submit_t)
        if is_probe:
            h.begin_probe()
        req.live_attempts[attempt] = (rid, now, is_probe)
        self._ledger[rid][(req.request_id, attempt)] = req
        self.metrics.dispatches.inc(kind=kind)
        return True

    # ---- hedging -----------------------------------------------------------

    def _hedge_threshold(self) -> Optional[float]:
        if self.config.hedge_after_s is not None:
            return self.config.hedge_after_s
        if len(self._service_lat) < self.config.hedge_min_samples:
            return None
        pct = float(np.percentile(
            np.asarray(self._service_lat), self.config.hedge_percentile
        ))
        return max(self.config.hedge_min_after_s, pct)

    def _hedge_sweep(self, now: float, newly_done: List[FleetRequest]):
        threshold = self._hedge_threshold()
        if threshold is None:
            return
        # Snapshot: dispatching mutates ledgers.
        inflight = {
            req.request_id: req
            for led in self._ledger.values()
            for req in led.values()
        }
        for req in inflight.values():
            if (
                req.done
                or req.hedged
                or len(req.live_attempts) != 1
                or req.max_new_tokens > self.config.hedge_max_new_tokens
            ):
                continue
            (rid, dispatch_t, _probe) = next(
                iter(req.live_attempts.values())
            )
            if now - dispatch_t <= threshold:
                continue
            other = self._pick_replica(
                now, exclude={rid}, strict_exclude=True
            )
            if other is None:
                continue
            if self._dispatch(req, other, "hedge", now, newly_done):
                req.hedged = True
                self.metrics.hedges.inc()
