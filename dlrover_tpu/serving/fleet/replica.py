"""Replica handles: a ServingEngine behind a mailbox with heartbeats.

The router never touches an engine directly — it talks to a
:class:`ReplicaHandle`: ``submit()`` drops work into the replica's
mailbox, ``poll()`` drains completion events, ``last_heartbeat()`` /
``alive()`` feed the health breaker. Two implementations share that
surface:

- :class:`ThreadReplica` — the engine runs on an in-process thread.
  Compiled step programs are shared across replicas with the same shape
  key (the engine's ``lru_cache``), so N replicas cost one compile.
  ``kill()`` poisons the loop (it exits without draining — in-flight
  work is lost and heartbeats stop), which is the thread-mode analogue
  of a crash; an injected ``fleet.replica.step`` raise does the same.
- :class:`SubprocessReplica` — the engine runs in a child process
  (:mod:`dlrover_tpu.serving.fleet.replica_worker`), the ``soak_worker``
  pattern: JSONL commands down stdin, JSONL events (completions +
  heartbeats) up stdout, fault schedules armed via the standard env
  rigging. ``kill()`` is a real SIGKILL — the chaos episode's replica
  death. ``restart()`` respawns a fresh generation.

Completion events are plain dicts (the wire format IS the in-process
format, so the router cannot care which mode a replica runs in)::

    {"kind": "done", "request_id": ..., "attempt": ..., "ok": bool,
     "tokens": [...], "truncated": bool, "failure_reason": "",
     "ttft_s": float|None}

Every event carries the replica's ``generation`` — a completion from a
pre-restart generation for an attempt the router already re-routed is
recognizably stale (the at-most-once key still wins; generations make
the logs honest).

Disaggregated serving (§36) extends the protocol with three control
ops (``send()``) and two upstream events, shared by both modes:

- down: ``{"op": "import", "request_id", "attempt", "payload"}``
  (base64 migration bytes — admit mid-stream via the paged engine's
  DECODE-entry path), ``{"op": "export", ...}`` (flag an in-flight
  request for export at its next DECODE boundary — the live-drain
  trigger), ``{"op": "release", ...}`` (importer acked: drop the
  source copy, recycle slot + blocks);
- up: ``{"kind": "exported", "request_id", "attempt", "payload"}``
  and ``{"kind": "imported", "request_id", "attempt", "ok", ...}``.

A replica whose engine cannot migrate (the flat slot pool) answers an
import with ``ok: false`` and simply never emits ``exported`` — the
router falls back to source-side completion, never an error.
"""

import base64
import json
import os
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Set

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point
from dlrover_tpu.fault.registry import SCHEDULE_ENV, TRACE_ENV


class ReplicaDeadError(RuntimeError):
    """submit() on a replica that cannot take work (process exited,
    thread gone, pipe closed). The router turns this into a re-route."""


@dataclass
class WorkItem:
    """One dispatch: a (request, attempt) pair bound for one replica.
    ``deadline_s`` is REMAINING seconds at dispatch (never an absolute
    timestamp — subprocess replicas have their own monotonic clock)."""

    request_id: str
    attempt: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    deadline_s: Optional[float] = None
    # Named SLO class (§31): forwarded into the engine scheduler's
    # weighted-fair admission; None = the engine's default class.
    slo_class: Optional[str] = None
    # Trace carrier of the router's attempt span ({"trace_id",
    # "span_id"} or None): the replica engine parents its phase spans
    # to it, so a rerouted request is one tree across processes.
    trace: Optional[dict] = None
    # Two-phase dispatch (§36): export this request's KV blocks as
    # soon as prefill completes (first token sampled) — the replica
    # emits an ``exported`` event and KEEPS the request live until the
    # router's ``release`` op (the importer's ack).
    migrate_after_prefill: bool = False

    def to_wire(self) -> dict:
        return {
            "op": "submit",
            "request_id": self.request_id,
            "attempt": self.attempt,
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
            "deadline_s": self.deadline_s,
            "slo_class": self.slo_class,
            "trace": self.trace,
            "migrate_after_prefill": self.migrate_after_prefill,
        }


def _completion(item_key, ok, tokens, truncated, failure_reason,
                ttft_s, generation=None) -> dict:
    request_id, attempt = item_key
    out = {
        "kind": "done",
        "request_id": request_id,
        "attempt": attempt,
        "ok": bool(ok),
        "tokens": list(tokens),
        "truncated": bool(truncated),
        "failure_reason": failure_reason,
        "ttft_s": ttft_s,
    }
    # generation=None (subprocess worker): omitted so the parent can
    # stamp its own at receipt (_read_events setdefault) — a worker
    # cannot know which respawn it is.
    if generation is not None:
        out["generation"] = generation
    return out


def serve_submit(engine, by_rid, emit, request_id, attempt, prompt,
                 max_new_tokens, temperature, deadline_s,
                 trace=None, slo_class=None):
    """One work item into the engine — shared by both replica modes so
    the wire behavior cannot drift. A scheduler rejection (prompt too
    long, bad deadline, unknown SLO class) is an EXPLICIT failed
    completion, never a crash: crashing here would cascade the poison
    request through the fleet. Returns the engine request (None on
    rejection) so callers can flag it for post-prefill export."""
    try:
        req = engine.submit(
            prompt, max_new_tokens,
            temperature=temperature, deadline_s=deadline_s,
            trace=trace, slo_class=slo_class,
        )
    except Exception:  # noqa: BLE001 — any rejection is the same event
        emit(_completion(
            (request_id, attempt),
            ok=False, tokens=(), truncated=False,
            failure_reason="rejected", ttft_s=None,
        ))
        return None
    by_rid[req.rid] = (request_id, attempt)
    return req


def serve_step(engine, by_rid, emit) -> None:
    """One engine iteration -> one completion event per finished
    request — shared by both replica modes."""
    for req in engine.step():
        key = by_rid.pop(req.rid, None)
        if key is None:
            continue  # engine-internal request (warmup etc.)
        emit(_completion(
            key,
            ok=not req.failed,
            tokens=req.tokens,
            truncated=req.truncated,
            failure_reason=req.failure_reason,
            ttft_s=req.ttft_s,
        ))


def serve_exports(engine, by_rid, emit, migrate_rids: Set[int]) -> None:
    """Export every flagged request that has reached DECODE (first
    token sampled): emit an ``exported`` event with the base64
    migration payload and KEEP the request live — the router decides
    between a destination import (followed by ``release``) and
    source-side completion. Shared by both replica modes. A flat
    engine (no block plane) simply unflags: the fallback is serving
    the decode locally, never an error."""
    if not migrate_rids:
        return
    for rid in list(migrate_rids):
        if rid not in by_rid:
            migrate_rids.discard(rid)  # finished before export fired
    if not migrate_rids:
        return
    from dlrover_tpu.serving.kvpool.migrate import export_request
    from dlrover_tpu.serving.scheduler import DECODE
    for req in list(getattr(engine.scheduler, "by_slot", ())):
        if req is None or req.rid not in migrate_rids:
            continue
        if req.state != DECODE or not req.tokens:
            continue  # still prefilling; try again next iteration
        migrate_rids.discard(req.rid)
        key = by_rid.get(req.rid)
        if key is None:
            continue
        try:
            payload = export_request(engine, req)
        except Exception as e:  # noqa: BLE001 — flat engine / torn
            # state: the local decode continues; the explicit error
            # event lets a draining router stop waiting for this key.
            logger.debug("export of rid %d failed", req.rid,
                         exc_info=True)
            emit({
                "kind": "exported",
                "request_id": key[0],
                "attempt": key[1],
                "error": type(e).__name__,
            })
            continue
        emit({
            "kind": "exported",
            "request_id": key[0],
            "attempt": key[1],
            "payload": base64.b64encode(payload).decode("ascii"),
        })


def serve_import(engine, by_rid, emit, cmd: dict) -> None:
    """Admit a migrated payload mid-stream (DECODE entry). Any failure
    — full destination, flat engine, malformed bytes — is an explicit
    ``ok: false`` ack, never a crash: the source still owns the
    request and completes it locally."""
    request_id = cmd["request_id"]
    attempt = cmd.get("attempt", 0)
    try:
        from dlrover_tpu.serving.kvpool.migrate import import_request

        payload = base64.b64decode(cmd["payload"])
        req = import_request(engine, payload, trace=cmd.get("trace"))
    except Exception as e:  # noqa: BLE001 — refusal IS the protocol
        emit({
            "kind": "imported", "request_id": request_id,
            "attempt": attempt, "ok": False,
            "reason": type(e).__name__,
        })
        return
    by_rid[req.rid] = (request_id, attempt)
    emit({
        "kind": "imported", "request_id": request_id,
        "attempt": attempt, "ok": True,
    })


def serve_release(engine, by_rid, cmd: dict) -> None:
    """The importer acked: drop the source copy (slot + blocks
    recycled, ``migrated`` outcome). A request that already finished
    locally (the source won the race) is a no-op — its completion is
    the router's at-most-once duplicate."""
    key = (cmd["request_id"], cmd.get("attempt", 0))
    rid = next((r for r, k in by_rid.items() if k == key), None)
    if rid is None:
        return
    by_rid.pop(rid, None)
    req = next(
        (q for q in getattr(engine.scheduler, "by_slot", ())
         if q is not None and q.rid == rid),
        None,
    )
    if req is not None:
        from dlrover_tpu.serving.kvpool.migrate import release_exported

        release_exported(engine, req)


def serve_control(engine, by_rid, emit, migrate_rids: Set[int],
                  cmd: dict) -> None:
    """Dispatch one §36 control op — shared by both replica modes."""
    op = cmd.get("op")
    if op == "import":
        serve_import(engine, by_rid, emit, cmd)
    elif op == "release":
        serve_release(engine, by_rid, cmd)
    elif op == "export":
        # Live drain: flag an in-flight request; serve_exports fires
        # at its next DECODE boundary (or immediately if already
        # decoding). Unknown key = already finished: nothing to do.
        key = (cmd["request_id"], cmd.get("attempt", 0))
        rid = next(
            (r for r, k in by_rid.items() if k == key), None
        )
        if rid is not None:
            migrate_rids.add(rid)


class ThreadReplica:
    """In-process replica: one serve-loop thread driving one engine.

    ``engine_factory`` is called ON the loop thread (first start pays
    any compile there, not on the router); each ``restart()`` builds a
    fresh engine — after a poisoned loop the old engine's host/device
    state is untrusted, exactly like the engine's own step-error
    recovery, and the compiled programs are cached anyway.
    """

    mode = "thread"

    def __init__(
        self,
        replica_id: str,
        engine_factory: Callable[[], object],
        clock: Callable[[], float] = time.monotonic,
        idle_sleep_s: float = 0.001,
        role: str = "mixed",
    ):
        self.replica_id = str(replica_id)
        self.role = role  # §36: "prefill" | "decode" | "mixed"
        self._engine_factory = engine_factory
        self._clock = clock
        self._idle_sleep_s = idle_sleep_s
        self._inbox: Deque[WorkItem] = deque()
        self._outbox: Deque[dict] = deque()
        self._lock = threading.Lock()
        self._hb = 0.0
        self._stop = threading.Event()
        self._poison = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self.generation = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._poison.clear()
        self._ready.clear()
        self._thread = threading.Thread(
            target=self._run,
            name=f"fleet-replica-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()

    def wait_ready(self, timeout: float = 60.0) -> bool:
        return self._ready.wait(timeout)

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def kill(self) -> None:
        """Simulated crash: the loop exits at its next iteration WITHOUT
        draining — in-flight work is lost, heartbeats stop."""
        self._poison.set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def restart(self) -> None:
        self.stop()
        if self._thread is not None and self._thread.is_alive():
            # Wedged loop that would not join: abandon it. The
            # generation guard in _run makes it exit at its next
            # iteration boundary, and any events it still emits carry
            # its old generation.
            self._thread = None
        with self._lock:
            self._inbox.clear()
        self.generation += 1
        self.start()

    # ---- router surface ----------------------------------------------------

    def submit(self, item: WorkItem) -> None:
        if not self.alive():
            raise ReplicaDeadError(
                f"replica {self.replica_id} is not running"
            )
        with self._lock:
            self._inbox.append(item)

    def send(self, payload: dict) -> None:
        """A §36 control op (import / export / release) into the
        mailbox — the in-process twin of the subprocess JSONL line."""
        if not self.alive():
            raise ReplicaDeadError(
                f"replica {self.replica_id} is not running"
            )
        with self._lock:
            self._inbox.append(dict(payload))

    def poll(self) -> List[dict]:
        out = []
        while True:
            try:
                out.append(self._outbox.popleft())
            except IndexError:
                return out

    def last_heartbeat(self) -> float:
        return self._hb

    # ---- serve loop --------------------------------------------------------

    def _run(self) -> None:
        generation = self.generation
        try:
            engine = self._engine_factory()
        except Exception:
            logger.exception(
                "replica %s engine build failed", self.replica_id
            )
            return
        self._ready.set()
        self._hb = self._clock()
        by_rid: Dict[int, tuple] = {}   # engine rid -> (request_id, attempt)
        migrate_rids: Set[int] = set()  # flagged for post-prefill export

        def emit(event: dict) -> None:
            event["generation"] = generation
            self._outbox.append(event)
        while not self._stop.is_set():
            if self.generation != generation:
                return  # abandoned by a restart while wedged
            if self._poison.is_set():
                return  # crash: no drain, no farewell, heartbeats stop
            try:
                fault_point("fleet.replica.step", replica=self.replica_id)
            except Exception:
                # Injected step fault = the loop dies silently, the way
                # a wedged device thread would. Detection is the
                # router's job (heartbeats + alive()).
                return
            try:
                fault_point(
                    "fleet.health.heartbeat", replica=self.replica_id
                )
                self._hb = self._clock()
            except Exception:
                pass  # dropped heartbeat: the breaker strikes accrue
            moved = False
            while True:
                with self._lock:
                    item = (
                        self._inbox.popleft() if self._inbox else None
                    )
                if item is None:
                    break
                if isinstance(item, WorkItem):
                    req = serve_submit(
                        engine, by_rid, emit,
                        item.request_id, item.attempt, item.prompt,
                        item.max_new_tokens, item.temperature,
                        item.deadline_s, trace=item.trace,
                        slo_class=item.slo_class,
                    )
                    if req is not None and item.migrate_after_prefill:
                        migrate_rids.add(req.rid)
                else:
                    serve_control(
                        engine, by_rid, emit, migrate_rids, item
                    )
                moved = True
            if engine.pending():
                serve_step(engine, by_rid, emit)
                moved = True
            serve_exports(engine, by_rid, emit, migrate_rids)
            if not moved:
                time.sleep(self._idle_sleep_s)


class SubprocessReplica:
    """Out-of-process replica over stdin/stdout JSONL (the
    ``soak_worker`` rigging pattern: env-armed fault schedules, fsynced
    fault traces, per-generation log files)."""

    mode = "subprocess"

    def __init__(
        self,
        replica_id: str,
        work_dir: str,
        slots: int = 2,
        max_len: int = 64,
        prefill_chunk: int = 8,
        heartbeat_s: float = 0.2,
        step_delay_ms: float = 0.0,
        token_delay_us: float = 0.0,
        schedule_path="",
        clock: Callable[[], float] = time.monotonic,
        paged: bool = False,
        block_size: int = 8,
        num_blocks: Optional[int] = None,
        role: str = "mixed",
    ):
        # ``schedule_path``: a str arms the same fault schedule on every
        # generation; a sequence indexes by generation ("" past the end)
        # — the soak-worker pattern, so a replica SIGKILLed by its gen-0
        # schedule comes back CLEAN and can actually recover instead of
        # deterministically re-dying at the same hit count forever.
        self.replica_id = str(replica_id)
        self.role = role  # §36: "prefill" | "decode" | "mixed"
        self._work_dir = work_dir
        self._slots = slots
        self._max_len = max_len
        self._prefill_chunk = prefill_chunk
        self._heartbeat_s = heartbeat_s
        self._step_delay_ms = step_delay_ms
        self._token_delay_us = token_delay_us
        self._schedule_path = schedule_path
        self._clock = clock
        self._paged = paged
        self._block_size = block_size
        self._num_blocks = num_blocks
        # Latest paged-KV allocator stats the worker piggybacked on a
        # heartbeat ({} until the first one); survives the process so
        # the chaos episode can assert block conservation even after a
        # SIGKILL. ``kv_violation`` records the first heartbeat whose
        # stats broke conservation (checked at receipt — a violation
        # mid-run must not be masked by a clean final state).
        self.last_kv: Dict = {}
        self.kv_violation: Optional[str] = None
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._outbox: Deque[dict] = deque()
        self._hb = 0.0
        self._ready = threading.Event()
        self._stdin_lock = threading.Lock()
        self.generation = 0
        os.makedirs(work_dir, exist_ok=True)

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            return
        import dlrover_tpu

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(dlrover_tpu.__file__)
        ))
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": repo_root + os.pathsep + env.get(
                "PYTHONPATH", ""
            ),
            TRACE_ENV: os.path.join(
                self._work_dir,
                f"trace_replica{self.replica_id}.jsonl",
            ),
        })
        from dlrover_tpu.observability import tracing as tracing_lib

        if tracing_lib.active_tracer() is not None:
            # Parent traces -> children trace too, each into its own
            # JSONL (a SIGKILLed replica's finished spans survive; the
            # soak merges the files). Disarmed parents rig nothing.
            env[tracing_lib.TRACE_FILE_ENV] = os.path.join(
                self._work_dir,
                f"spans_replica{self.replica_id}.jsonl",
            )
        else:
            env.pop(tracing_lib.TRACE_FILE_ENV, None)
        sched = self._schedule_path
        if not isinstance(sched, str):
            sched = (
                sched[self.generation]
                if self.generation < len(sched) else ""
            )
        if sched:
            env[SCHEDULE_ENV] = sched
        else:
            env.pop(SCHEDULE_ENV, None)
        args = [
            sys.executable, "-m",
            "dlrover_tpu.serving.fleet.replica_worker",
            "--replica-id", self.replica_id,
            "--slots", str(self._slots),
            "--max-len", str(self._max_len),
            "--prefill-chunk", str(self._prefill_chunk),
            "--heartbeat-s", str(self._heartbeat_s),
            "--step-delay-ms", str(self._step_delay_ms),
        ]
        if self._token_delay_us > 0:
            args += ["--token-delay-us", str(self._token_delay_us)]
        if self._paged:
            args += ["--paged", "--block-size", str(self._block_size)]
            if self._num_blocks is not None:
                args += ["--num-blocks", str(self._num_blocks)]
        log_path = os.path.join(
            self._work_dir,
            f"replica{self.replica_id}_gen{self.generation}.log",
        )
        self._ready.clear()
        with open(log_path, "w") as log:
            # The child duplicates the fd; closing the parent handle
            # keeps long fleets from accumulating fds.
            self._proc = subprocess.Popen(
                args, env=env, cwd=repo_root,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=log, text=True, bufsize=1,
            )
        self._reader = threading.Thread(
            target=self._read_events,
            args=(self._proc, self.generation),
            name=f"fleet-replica-{self.replica_id}-reader",
            daemon=True,
        )
        self._reader.start()

    def wait_ready(self, timeout: float = 60.0) -> bool:
        return self._ready.wait(timeout)

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def kill(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(timeout=5)

    def stop(self) -> None:
        if self._proc is None:
            return
        if self._proc.poll() is None:
            try:
                self._send({"op": "stop"})
                self._proc.wait(timeout=5)
            except (ReplicaDeadError, subprocess.TimeoutExpired):
                self._proc.kill()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
        if self._reader is not None:
            self._reader.join(timeout=2)

    def restart(self) -> None:
        self.stop()
        self.generation += 1
        self.start()

    # ---- router surface ----------------------------------------------------

    def submit(self, item: WorkItem) -> None:
        self._send(item.to_wire())

    def send(self, payload: dict) -> None:
        """A §36 control op as a JSONL line (the ThreadReplica twin)."""
        self._send(payload)

    def poll(self) -> List[dict]:
        out = []
        while True:
            try:
                out.append(self._outbox.popleft())
            except IndexError:
                return out

    def last_heartbeat(self) -> float:
        return self._hb

    # ---- internals ---------------------------------------------------------

    def _send(self, payload: dict) -> None:
        if not self.alive():
            raise ReplicaDeadError(
                f"replica {self.replica_id} process is not running"
            )
        line = json.dumps(payload) + "\n"
        try:
            with self._stdin_lock:
                self._proc.stdin.write(line)
                self._proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError) as e:
            raise ReplicaDeadError(
                f"replica {self.replica_id} pipe closed: {e}"
            ) from e

    def _read_events(self, proc: subprocess.Popen, generation: int):
        """Drain the child's stdout until EOF (exit/SIGKILL). Heartbeats
        update the timestamp in place; completions queue for poll().
        The heartbeat is stamped with the PARENT clock at receipt — the
        breaker compares against the router's clock, and a dead child's
        last self-reported time would lie about when it was last seen."""
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue  # torn line from a SIGKILL mid-write
                kind = event.get("kind")
                if kind == "heartbeat":
                    self._hb = self._clock()
                    kv = event.get("kv")
                    if kv:
                        self.last_kv = kv
                        self._check_kv(kv)
                elif kind == "ready":
                    self._hb = self._clock()
                    self._ready.set()
                elif kind in ("done", "exported", "imported"):
                    event.setdefault("generation", generation)
                    self._hb = self._clock()
                    self._outbox.append(event)
        except (OSError, ValueError):
            pass

    def _check_kv(self, kv: dict) -> None:
        """Block conservation, checked at heartbeat RECEIPT: free +
        used + cached must sum to the managed pool and no refcount may
        go negative. The first violation is pinned — the chaos
        episode's block-reclaim invariant reads it after the drain."""
        if self.kv_violation is not None:
            return
        try:
            total = kv["free"] + kv["used"] + kv["cached"]
            if total != kv["total"]:
                self.kv_violation = (
                    f"replica {self.replica_id}: free {kv['free']} + "
                    f"used {kv['used']} + cached {kv['cached']} = "
                    f"{total} != total {kv['total']}"
                )
            elif kv.get("negative_refs", 0):
                self.kv_violation = (
                    f"replica {self.replica_id}: "
                    f"{kv['negative_refs']} negative refcount(s)"
                )
        except (KeyError, TypeError) as e:
            self.kv_violation = (
                f"replica {self.replica_id}: malformed kv stats "
                f"{kv!r}: {e}"
            )
