"""Per-replica health: a deterministic, clock-injectable circuit
breaker.

The router must keep dispatching while replicas die, stall, or flap —
so each replica gets one small state machine, advanced ONLY by explicit
inputs (successes, failures, heartbeats, liveness) and an injectable
clock, never by wall-time side effects. That is what makes the FSM
unit-testable without sleeps (the `TrainingHangDiagnostician` pattern
from the fault plane) and its transitions reproducible in chaos soaks.

States::

    HEALTHY ──consecutive failures >= suspect_after──▶ SUSPECT
    SUSPECT ──one success──▶ HEALTHY
    SUSPECT ──consecutive failures >= broken_after──▶ BROKEN
    any     ──mark_dead() (process exit, poison)──▶ BROKEN
    BROKEN  ──probe_cooldown_s elapsed + dispatch wanted──▶ HALF_OPEN
    HALF_OPEN ──probe_successes successes──▶ HEALTHY
    HALF_OPEN ──any failure──▶ BROKEN (cooldown restarts)

SUSPECT still takes traffic (it is a *warning* state — deprioritized by
the router's least-loaded choice, not fenced), BROKEN takes none,
HALF_OPEN takes a bounded number of in-flight probe requests (real
traffic used as canaries). Missed heartbeats count as failures: one
strike per elapsed ``heartbeat_timeout_s`` window, so a stalled-but-
alive replica walks HEALTHY → SUSPECT → BROKEN on the same path an
erroring one does.
"""

import time
from dataclasses import dataclass
from typing import Callable, Optional

HEALTHY = "healthy"
SUSPECT = "suspect"
BROKEN = "broken"
HALF_OPEN = "half_open"

# Gauge encoding for the per-replica state metric family.
STATE_CODE = {HEALTHY: 0, SUSPECT: 1, BROKEN: 2, HALF_OPEN: 3}


@dataclass
class HealthPolicy:
    """Thresholds; all deterministic counters/durations."""

    suspect_after: int = 2        # consecutive failures HEALTHY->SUSPECT
    broken_after: int = 4         # consecutive failures ->BROKEN
    heartbeat_timeout_s: float = 2.0
    probe_cooldown_s: float = 1.0  # BROKEN quarantine before HALF_OPEN
    probe_successes: int = 2      # HALF_OPEN successes to re-admit
    max_probes_inflight: int = 1  # concurrent canaries while HALF_OPEN

    def __post_init__(self):
        if self.suspect_after < 1 or self.broken_after < self.suspect_after:
            raise ValueError(
                "need 1 <= suspect_after <= broken_after, got "
                f"{self.suspect_after}/{self.broken_after}"
            )


class ReplicaHealth:
    """One replica's breaker. Not thread-safe by design — the router's
    single pump thread owns every transition."""

    def __init__(
        self,
        replica_id: str,
        policy: Optional[HealthPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        self.replica_id = str(replica_id)
        self.policy = policy or HealthPolicy()
        self._clock = clock
        self._on_transition = on_transition
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.last_failure_reason = ""
        self._broken_since: Optional[float] = None
        self._probe_successes = 0
        self.probes_inflight = 0
        now = clock()
        self._last_heartbeat = now
        # Next time a stale heartbeat earns a strike; re-armed by every
        # real heartbeat, advanced by every strike so one long stall
        # escalates once per timeout window, not once per check() call.
        self._next_hb_strike = now + self.policy.heartbeat_timeout_s

    # ---- inputs ------------------------------------------------------------

    def observe_heartbeat(self, t: Optional[float] = None) -> None:
        t = self._clock() if t is None else t
        if t > self._last_heartbeat:
            self._last_heartbeat = t
            self._next_hb_strike = t + self.policy.heartbeat_timeout_s

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state == HALF_OPEN:
            self._probe_successes += 1
            if self._probe_successes >= self.policy.probe_successes:
                self._transition(HEALTHY)
        elif self.state == SUSPECT:
            self._transition(HEALTHY)

    def record_failure(self, reason: str = "error") -> None:
        self.last_failure_reason = reason
        if self.state == HALF_OPEN:
            # A failed canary slams the breaker shut; cooldown restarts.
            self._break()
            return
        if self.state == BROKEN:
            return
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.policy.broken_after:
            self._break()
        elif (
            self.state == HEALTHY
            and self.consecutive_failures >= self.policy.suspect_after
        ):
            self._transition(SUSPECT)

    def mark_dead(self, reason: str = "dead") -> None:
        """Hard evidence (process exited, thread gone): straight to
        BROKEN, no strike accumulation."""
        self.last_failure_reason = reason
        if self.state != BROKEN:
            self._break()

    def check(self, now: Optional[float] = None) -> None:
        """Advance time-driven transitions: missed-heartbeat strikes.
        Call once per router pump iteration."""
        now = self._clock() if now is None else now
        if self.state == BROKEN:
            return
        while now >= self._next_hb_strike:
            self._next_hb_strike += self.policy.heartbeat_timeout_s
            self.record_failure("heartbeat")
            if self.state == BROKEN:
                return

    # ---- dispatch gate -----------------------------------------------------

    def dispatchable(self, now: Optional[float] = None) -> bool:
        """May the router hand this replica a request right now? A
        BROKEN breaker whose cooldown elapsed flips to HALF_OPEN here —
        the transition is demand-driven, so quarantine costs nothing
        when no traffic wants the replica."""
        now = self._clock() if now is None else now
        if self.state in (HEALTHY, SUSPECT):
            return True
        if self.state == BROKEN:
            if (
                self._broken_since is not None
                and now - self._broken_since >= self.policy.probe_cooldown_s
            ):
                self._transition(HALF_OPEN)
                self._probe_successes = 0
                self.probes_inflight = 0
            else:
                return False
        return self.probes_inflight < self.policy.max_probes_inflight

    def is_probe_dispatch(self) -> bool:
        return self.state == HALF_OPEN

    def begin_probe(self) -> None:
        self.probes_inflight += 1

    def end_probe(self) -> None:
        self.probes_inflight = max(0, self.probes_inflight - 1)

    def heartbeat_age(self, now: Optional[float] = None) -> float:
        """Seconds since the last observed heartbeat — the router's
        wedge detector (BROKEN + stale heartbeat = hung, not erroring)."""
        now = self._clock() if now is None else now
        return now - self._last_heartbeat

    def cooldown_elapsed(self, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        return (
            self.state == BROKEN
            and self._broken_since is not None
            and now - self._broken_since >= self.policy.probe_cooldown_s
        )

    # ---- internals ---------------------------------------------------------

    def _break(self) -> None:
        self._broken_since = self._clock()
        self._probe_successes = 0
        self.probes_inflight = 0
        self._transition(BROKEN)

    def _transition(self, new: str) -> None:
        old, self.state = self.state, new
        if new == HEALTHY:
            self.consecutive_failures = 0
            self._broken_since = None
        if new in (HEALTHY, HALF_OPEN):
            # A fresh start (or a probe window after a long BROKEN
            # quarantine) gets a fresh heartbeat grace window — the
            # stale strikes accumulated while fenced must not instantly
            # re-break the breaker before the first probe lands.
            self._next_hb_strike = (
                self._clock() + self.policy.heartbeat_timeout_s
            )
        if old != new and self._on_transition is not None:
            self._on_transition(old, new)
