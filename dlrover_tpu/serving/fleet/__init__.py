"""Self-healing serving fleet: a health-gated router over N engine
replicas — deadlines, bounded retries, hedging, load shedding, crash
re-routing. See router.py for the contract and docs/DESIGN.md §28."""

from dlrover_tpu.serving.fleet.health import (
    BROKEN,
    HALF_OPEN,
    HEALTHY,
    SUSPECT,
    HealthPolicy,
    ReplicaHealth,
)
from dlrover_tpu.serving.fleet.metrics import fleet_metrics
from dlrover_tpu.serving.fleet.replica import (
    ReplicaDeadError,
    SubprocessReplica,
    ThreadReplica,
    WorkItem,
)
from dlrover_tpu.serving.fleet.router import (
    FleetRequest,
    FleetResult,
    FleetRouter,
    RouterConfig,
)

__all__ = [
    "FleetRouter",
    "RouterConfig",
    "FleetRequest",
    "FleetResult",
    "ThreadReplica",
    "SubprocessReplica",
    "WorkItem",
    "ReplicaDeadError",
    "ReplicaHealth",
    "HealthPolicy",
    "HEALTHY",
    "SUSPECT",
    "BROKEN",
    "HALF_OPEN",
    "fleet_metrics",
]
