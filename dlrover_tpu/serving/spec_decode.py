"""Self-speculative decoding: drafters + the batched accept/reject law.

Decode buys exactly one token per weight/KV sweep; BENCH_SELF pins
that sweep at 1.33-1.46x the HBM roofline, so the remaining raw-speed
axis is tokens PER step (ROADMAP item 2). Speculative decoding
(Leviathan-style draft-then-verify, self-drafting so no second model
needs sharding) drafts K cheap continuation tokens per slot, then
verifies all K in ONE batched forward through the existing ragged
attention — every accepted draft is a free token amortized onto the
verification sweep.

Two drafters, both derived from the serving model itself:

- **n-gram / prompt lookup** (:func:`propose_ngram`) — pure host-side
  suffix matching over the request's own prompt + generated tokens.
  Zero device cost, and strong exactly where speculation pays most
  (repetitive suffixes: code, extraction, templated text).
- **early exit** — a truncated-layer forward through the FIRST
  ``draft_layers`` decoder blocks of the same weights, reusing the
  live decode cache (drafted partial-layer K/V lands beyond the fill,
  where the visibility invariant keeps it unread until the verify
  pass rewrites those rows with full-model values). Built per engine
  (serving/engine.py, serving/kvpool/engine.py) because the cache
  plumbing differs; the proposal rule is shared greedy argmax.

Verification + rollback (:func:`spec_accept`): the verify step scores
the fed token plus K drafts in one call, then this acceptance law runs
ON DEVICE — greedy rows accept a draft iff it IS the argmax (token-
exact vs the non-speculative baseline by construction), sampled rows
use standard rejection sampling against the deterministic drafter
(accept draft d with prob p(d); on rejection sample the residual — p
with d masked out, the exact distribution-correcting rule), and the
first rejection truncates the chain (cumulative product). Rollback is
FREE: rejected rows sit beyond the advanced fill and the visibility
invariant ("rows visible iff < fill", docs/DESIGN.md SS25/SS31/SS35)
guarantees no cleanup pass exists.
"""

import numpy as np

import jax
import jax.numpy as jnp

from dlrover_tpu.models import generate as gen_lib

SPEC_DRAFTERS = ("ngram", "early_exit")


def propose_ngram(
    history: np.ndarray, k: int, max_ngram: int = 3
) -> np.ndarray:
    """Prompt-lookup draft: match the sequence's own recent suffix.

    Finds the RIGHTMOST earlier occurrence of the longest suffix
    n-gram (``max_ngram`` down to 1) of ``history`` and proposes the
    up-to-``k`` tokens that followed it. Host-only numpy — the
    zero-cost drafter; returns an empty array when nothing matches
    (the engine then degenerates to plain one-token decode for that
    slot, draft_len 0)."""
    history = np.asarray(history, np.int32).reshape(-1)
    n = int(history.shape[0])
    if k <= 0 or n < 2:
        return np.zeros(0, np.int32)
    for g in range(min(max_ngram, n - 1), 0, -1):
        pat = history[n - g:]
        # Candidate starts: 0..n-g-1 (a window ending before the
        # suffix itself, so a continuation token exists). g shifted
        # equality masks beat materializing an [n, g] window matrix —
        # this runs per decoding slot per verify step.
        mask = history[: n - g] == pat[0]
        for j in range(1, g):
            mask &= history[j : j + n - g] == pat[j]
        hits = np.nonzero(mask)[0]
        if hits.size:
            s = int(hits[-1])
            cont = history[s + g : s + g + k]
            if cont.size:
                return cont.astype(np.int32)
    return np.zeros(0, np.int32)


def spec_accept(
    logits,      # [slots, T, V] f32 — verify logits, T = K+1
    drafts,      # [slots, K] int32 — drafted tokens
    draft_len,   # [slots] int32 — valid drafts per slot (0..K)
    temps,       # [slots] f32 — per-slot temperature, <= 0 greedy
    active,      # [slots] bool
    fed_tokens,  # [slots] int32 — the fed token (stable inactive fill)
    rng,
    step_idx,
):
    """The batched accept/reject law; runs inside the verify program.

    Greedy rows (t <= 0): draft i+1 accepted iff it equals
    ``argmax(logits[i])`` — the emitted chain is bit-identical to what
    sequential greedy decode would have produced, because each
    position's logits ARE the sequential step's logits (the verify
    attention reproduces the per-step math exactly).

    Sampled rows: the drafters are deterministic (q = a point mass on
    the drafted token), so Leviathan rejection sampling reduces to:
    accept draft d_i with probability p_i(d_i); on the first rejection
    sample the correction from the residual — p_i with d_i masked out,
    renormalized — and when every draft survives, sample the bonus
    token from the model's own next distribution. Both final picks go
    through :func:`gen_lib.sample_token_logprobs` (one call: greedy
    rows mask nothing that can win, so the same masked pick is exact
    argmax for them too).

    Returns ``(emitted [slots, T] int32, accept_len [slots] int32)``:
    ``emitted[s, :accept_len[s]]`` are the accepted drafts and
    ``emitted[s, accept_len[s]]`` the correction/bonus token — the
    host appends ``accept_len + 1`` tokens and advances the fill by
    the same amount (rejected rows stay beyond the fill: free
    rollback)."""
    from dlrover_tpu.ops.attention import NEG_INF

    slots, T, V = logits.shape
    K = T - 1
    drafts = drafts.astype(jnp.int32)
    m = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [slots, T]
    greedy_ok = drafts == m[:, :K]
    tcol = jnp.asarray(temps, jnp.float32)[:, None]     # [slots, 1]
    base = jax.random.fold_in(rng, step_idx * 2)
    if K:
        scaled = logits[:, :K] / jnp.maximum(tcol, 1e-6)[..., None]
        logp = jax.nn.log_softmax(scaled, axis=-1)      # [slots, K, V]
        p_draft = jnp.take_along_axis(
            logp, drafts[..., None], axis=-1
        )[..., 0]                                       # [slots, K]
        u = jax.random.uniform(
            jax.random.fold_in(base, 1), (slots, K),
            minval=1e-20, maxval=1.0,
        )
        sampled_ok = jnp.log(u) < p_draft
        ok = jnp.where(tcol > 0.0, sampled_ok, greedy_ok)
        valid = jnp.arange(K)[None, :] < draft_len[:, None]
        ok = ok & valid
        acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)
        a = jnp.sum(acc, axis=1).astype(jnp.int32)      # [slots] 0..K
    else:
        a = jnp.zeros((slots,), jnp.int32)
    # Final pick at position a: a < draft_len -> rejection CORRECTION
    # (residual: the rejected draft is masked out); a == draft_len ->
    # BONUS token from the model's own distribution (no mask). Greedy
    # rows: the mask can only remove a non-argmax token (rejection
    # means draft != argmax), so the masked argmax is the plain argmax.
    logits_a = jnp.take_along_axis(
        logits, a[:, None, None], axis=1
    )[:, 0]                                             # [slots, V]
    if K:
        rejected = a < draft_len
        d_a = jnp.take_along_axis(
            drafts, jnp.minimum(a, K - 1)[:, None], axis=1
        )[:, 0]
        mask = rejected[:, None] & (
            jnp.arange(V)[None, :] == d_a[:, None]
        )
        logits_a = jnp.where(mask, NEG_INF, logits_a)
    t_fin, _ = gen_lib.sample_token_logprobs(
        logits_a, jax.random.fold_in(base, 2), temps
    )
    active = jnp.asarray(active)
    t_fin = jnp.where(active, t_fin, fed_tokens)
    a = jnp.where(active, a, 0)
    pos = jnp.arange(T)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((slots, 1), jnp.int32)], axis=1
    )
    emitted = jnp.where(pos < a[:, None], drafts_pad, t_fin[:, None])
    return emitted, a


def clamp_draft_len(
    k: int, tokens_done: int, max_new_tokens: int,
    fill: int, max_len: int,
) -> int:
    """Per-slot draft budget: never draft past the request's remaining
    token budget (the verify step always emits one final token on top
    of the accepted drafts) or past the cache rows that can become
    visible (``fill + accepted + 1 <= max_len``). The ONE clamp shared
    by both engines and both drafters — the host-side half of the
    scheduler's verification-token accounting."""
    room_tokens = max_new_tokens - tokens_done - 1
    room_rows = max_len - 1 - fill
    return max(0, min(k, room_tokens, room_rows))
